"""Failure detection + checkpoint-restart recovery tests, plus the
resilience-subsystem units: checkpoint integrity (CRC32/fingerprint),
corrupt-checkpoint fallback, the single-sync finite guard, watchdog
deadlines, preemption, and distributed/elastic restore parity.  The
end-to-end subprocess drill matrix lives in tests/test_drills.py."""

import contextlib
import math
import os
import signal

import numpy as np
import pytest

from roc_tpu.core.graph import synthetic_dataset
from roc_tpu.models.gcn import build_gcn
from roc_tpu.train.trainer import TrainConfig, Trainer
from roc_tpu.utils.resilience import (CheckpointRotation, NumericFailure,
                                      check_finite, train_with_recovery)


@pytest.fixture(scope="module", autouse=True)
def _shed_native_jit_state():
    """This module builds many short-lived trainers (plus the jitted
    all-finite guard); shed the accumulated native JIT state at module
    end — the PR-7 mitigation for the known jaxlib-0.4.x XLA:CPU
    corruption flake under per-process compile churn (test_flat_sum /
    test_mixed_precision / test_drills carry the same fixture)."""
    yield
    import jax
    jax.clear_caches()


@contextlib.contextmanager
def _capture_events():
    """Attach a list sink to the event bus for the duration."""
    from roc_tpu.obs.events import get_bus

    class _Cap:
        def __init__(self):
            self.records = []

        def write(self, rec):
            self.records.append(dict(rec))

        def close(self):
            pass

    bus = get_bus()
    cap = _Cap()
    bus.add_sink(cap)
    try:
        yield cap.records
    finally:
        bus.sinks.remove(cap)


@pytest.fixture()
def trainer():
    ds = synthetic_dataset(64, 6, in_dim=8, num_classes=3, seed=0)
    cfg = TrainConfig(epochs=100, eval_every=2, verbose=False,
                      symmetric=True)
    return Trainer(build_gcn([8, 8, 3]), ds, cfg)


def test_check_finite():
    check_finite({"train_loss": 1.0, "epoch": 3})
    with pytest.raises(NumericFailure):
        check_finite({"train_loss": float("nan"), "epoch": 3})
    with pytest.raises(NumericFailure):
        check_finite({"train_loss": float("inf"), "epoch": 3})


def test_rotation_keeps_last_k(trainer, tmp_path):
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2)
    for _ in range(4):
        trainer.train(epochs=1)
        rot.save(trainer)
    assert rot.existing() == [3, 4]


def test_recovery_resumes_after_crash(trainer, tmp_path):
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2)
    train_with_recovery(trainer, 6, rot, checkpoint_every=3)
    assert trainer.epoch == 6
    # simulate a process crash: brand-new trainer, same command
    ds = synthetic_dataset(64, 6, in_dim=8, num_classes=3, seed=0)
    cfg = TrainConfig(epochs=100, eval_every=2, verbose=False,
                      symmetric=True)
    t2 = Trainer(build_gcn([8, 8, 3]), ds, cfg)
    train_with_recovery(t2, 10, rot, checkpoint_every=3)
    assert t2.epoch == 10


def test_recovery_retries_on_numeric_failure(trainer, tmp_path):
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2)
    train_with_recovery(trainer, 2, rot, checkpoint_every=2)
    fails = {"n": 0}
    orig_train = trainer.train

    def flaky_train(epochs=None):
        hist = orig_train(epochs=epochs)
        if fails["n"] < 2:
            fails["n"] += 1
            hist[-1]["train_loss"] = float("nan")
        return hist

    trainer.train = flaky_train
    seen = []
    train_with_recovery(trainer, 6, rot, checkpoint_every=2,
                        max_retries=3,
                        on_failure=lambda e: seen.append(str(e)))
    assert trainer.epoch == 6
    assert len(seen) == 2


def test_recovery_gives_up_after_max_retries(trainer, tmp_path):
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2)
    train_with_recovery(trainer, 2, rot, checkpoint_every=2)
    orig_train = trainer.train

    def always_nan(epochs=None):
        hist = orig_train(epochs=epochs)
        hist[-1]["train_loss"] = float("nan")
        return hist

    trainer.train = always_nan
    with pytest.raises(NumericFailure):
        train_with_recovery(trainer, 8, rot, checkpoint_every=2,
                            max_retries=1)


def test_recovery_retries_transient_io_error(trainer, tmp_path):
    """OSError from a training round (the streamed tier's staging
    path, storage hiccups) is a recoverable class: restore + retry."""
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2)
    train_with_recovery(trainer, 2, rot, checkpoint_every=2)
    orig_train = trainer.train
    fails = {"n": 0}

    def flaky_io(epochs=None):
        if fails["n"] < 1:
            fails["n"] += 1
            raise OSError("injected transient staging failure")
        return orig_train(epochs=epochs)

    trainer.train = flaky_io
    train_with_recovery(trainer, 6, rot, checkpoint_every=2,
                        max_retries=2)
    assert trainer.epoch == 6


# ---- checkpoint integrity: v3 manifest/shards, CRC32, fingerprint ----

def _fresh_trainer(num_nodes=64, seed=0):
    ds = synthetic_dataset(num_nodes, 6, in_dim=8, num_classes=3,
                           seed=seed)
    cfg = TrainConfig(epochs=100, eval_every=2, verbose=False,
                      symmetric=True)
    return Trainer(build_gcn([8, 8, 3]), ds, cfg)


def _ckpt_file(path):
    """The byte-flippable artifact of a checkpoint: a v3 directory's
    first shard file, or the legacy single file itself."""
    if os.path.isdir(path):
        shard = sorted(n for n in os.listdir(path)
                       if n.startswith("shard_"))[0]
        return os.path.join(path, shard)
    return path


def _flip_byte(path, offset=None):
    path = _ckpt_file(str(path))
    size = os.path.getsize(path)
    off = size // 2 if offset is None else offset
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))


def _legacy_arrays(trainer):
    """The v1/v2-style flat array dict of a trainer's state (the
    migration tests build legacy files from it by hand — the writers
    are gone, the loaders must stay)."""
    import jax
    from roc_tpu.utils.checkpoint import _flatten
    data = _flatten(jax.device_get(trainer.params), "params")
    data.update(_flatten(jax.device_get(trainer.opt_state), "opt"))
    data["__epoch__"] = np.asarray(trainer.epoch, dtype=np.int64)
    data["__key__"] = np.asarray(jax.device_get(trainer.key))
    return data


def test_checkpoint_v3_manifest_and_roundtrip(trainer, tmp_path):
    import json
    from roc_tpu.utils.checkpoint import (checkpoint_trainer,
                                          read_manifest,
                                          restore_trainer)
    trainer.train(epochs=2)
    p = str(tmp_path / "ck")
    checkpoint_trainer(trainer, p)
    # the v3 directory layout: per-process shard + committed manifest
    assert sorted(os.listdir(p)) == ["MANIFEST.json",
                                     "shard_00000.npz"]
    man = read_manifest(p)
    assert man["version"] == 3
    assert man["epoch"] == 2
    sh = man["shards"][0]
    assert sh["file"] == "shard_00000.npz" and sh["crc32"]
    assert sh["bytes"] == os.path.getsize(
        os.path.join(p, "shard_00000.npz"))
    fp = man["fingerprint"]
    assert fp["strict"]["params_sig"]
    assert fp["strict"]["dataset"] == {"V": 64, "E": trainer._obs_edges}
    assert fp["elastic"]["num_parts"] == 1
    # the shard header carries per-array CRCs + the sharding-spec
    # vocabulary (global shape / per-dim axis spec / piece index)
    with np.load(os.path.join(p, "shard_00000.npz")) as z:
        header = json.loads(bytes(
            np.asarray(z["__header__"], dtype=np.uint8)).decode())
    assert header["version"] == 3 and header["process"] == 0
    assert header["crc32"]
    some = next(k for k in header["arrays"] if k.startswith("params"))
    meta = header["arrays"][some]
    assert meta["shape"] and meta["dtype"]
    assert all(s is None for s in meta["spec"])  # replicated today
    t2 = _fresh_trainer()
    restore_trainer(t2, p)
    assert t2.epoch == 2
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(trainer.params),
                    jax.tree_util.tree_leaves(t2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_corrupt_checkpoint_raises_distinct_error(trainer, tmp_path):
    """The PR-7 denormal-garbage corruption class: a flipped shard
    byte must surface as CheckpointCorrupt (manifest-vs-shard CRC),
    never as silently-wrong params."""
    from roc_tpu.utils.checkpoint import (CheckpointCorrupt,
                                          checkpoint_trainer,
                                          restore_trainer)
    trainer.train(epochs=1)
    p = str(tmp_path / "ck")
    checkpoint_trainer(trainer, p)
    _flip_byte(p)
    with pytest.raises(CheckpointCorrupt):
        restore_trainer(trainer, p)


def test_uncommitted_checkpoint_is_invisible(trainer, tmp_path):
    """A v3 directory without MANIFEST.json (a save that died before
    the commit) must raise CheckpointCorrupt on a direct load and be
    invisible to the rotation scan."""
    from roc_tpu.utils.checkpoint import (CheckpointCorrupt,
                                          checkpoint_trainer,
                                          restore_trainer)
    trainer.train(epochs=1)
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=3)
    rot.save(trainer)
    p = rot.path(trainer.epoch)
    os.remove(os.path.join(p, "MANIFEST.json"))
    assert rot.existing() == []
    with pytest.raises(CheckpointCorrupt, match="manifest"):
        restore_trainer(trainer, p)


def test_rotation_falls_back_on_deleted_shard(trainer, tmp_path):
    """ISSUE 15 satellite regression: the corrupt-fallback scan must
    validate the manifest AND every listed shard before selecting a
    candidate — a committed manifest whose shard file went missing
    must fall through to the previous checkpoint, not be accepted."""
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=3)
    trainer.train(epochs=1)
    rot.save(trainer)
    trainer.train(epochs=1)
    rot.save(trainer)
    assert rot.existing() == [1, 2]
    newest = rot.path(2)
    os.remove(_ckpt_file(newest))
    # the manifest is still committed, so the scan SEES the epoch...
    assert rot.existing() == [1, 2]
    t2 = _fresh_trainer()
    with _capture_events() as recs:
        # ...but full validation rejects it before selection
        assert rot.restore_latest(t2) == 1
    assert t2.epoch == 1
    falls = [r for r in recs if r.get("kind") == "corrupt_fallback"]
    assert falls and "missing" in falls[0]["msg"]


def test_rotation_migrates_legacy_files(trainer, tmp_path):
    """A rotation holding a legacy v2 .npz restores it, a torn v3
    directory at the SAME epoch never shadows it, and the next saves
    write v3 directories — the in-place migration path."""
    import json
    import zlib
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2)
    trainer.train(epochs=1)
    data = _legacy_arrays(trainer)
    crc = {k: zlib.crc32(np.ascontiguousarray(v).tobytes())
           & 0xFFFFFFFF for k, v in data.items()}
    data["__header__"] = np.frombuffer(json.dumps(
        {"version": 2, "crc32": crc, "fingerprint": {}}).encode(),
        dtype=np.uint8)
    np.savez(str(tmp_path / "ck.1.npz"), **data)
    # a torn (uncommitted) v3 dir at the same epoch: must not shadow
    os.makedirs(tmp_path / "ck.1")
    assert rot.existing() == [1]
    t2 = _fresh_trainer()
    assert rot.restore_latest(t2) == 1
    assert t2.epoch == 1
    # saves continue in v3; prune clears BOTH legacy forms
    for _ in range(2):
        t2.train(epochs=1)
        rot.save(t2)
    assert rot.existing() == [2, 3]
    assert not (tmp_path / "ck.1.npz").exists()
    assert (tmp_path / "ck.3" / "MANIFEST.json").exists()


def test_v1_checkpoint_loads_with_warning(trainer, tmp_path):
    """Pre-header single-file checkpoints still restore — with a loud
    resilience event instead of validation."""
    from roc_tpu.utils.checkpoint import restore_trainer
    trainer.train(epochs=1)
    p1 = str(tmp_path / "v1.npz")
    np.savez(p1, **_legacy_arrays(trainer))
    t2 = _fresh_trainer()
    with _capture_events() as recs:
        restore_trainer(t2, p1)
    assert t2.epoch == trainer.epoch
    assert any(r.get("cat") == "resilience"
               and r.get("kind") == "v1_checkpoint" for r in recs)


def test_v2_checkpoint_loads_with_warning(trainer, tmp_path):
    """Legacy v2 single-file checkpoints (header + per-array CRCs)
    still restore, fully validated, with the loud migration event."""
    import json
    import zlib
    from roc_tpu.utils.checkpoint import restore_trainer
    trainer.train(epochs=1)
    data = _legacy_arrays(trainer)
    crc = {k: zlib.crc32(np.ascontiguousarray(v).tobytes())
           & 0xFFFFFFFF for k, v in data.items()}
    header = {"version": 2, "crc32": crc, "fingerprint": {}}
    data["__header__"] = np.frombuffer(
        json.dumps(header).encode(), dtype=np.uint8)
    p2 = str(tmp_path / "v2.npz")
    np.savez(p2, **data)
    t2 = _fresh_trainer()
    with _capture_events() as recs:
        restore_trainer(t2, p2)
    assert t2.epoch == trainer.epoch
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(trainer.params),
                    jax.tree_util.tree_leaves(t2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert any(r.get("kind") == "legacy_checkpoint" for r in recs)


def test_fingerprint_mismatch_raises(trainer, tmp_path):
    """Same param shapes, different dataset: the strict fingerprint
    half refuses the restore loudly."""
    from roc_tpu.utils.checkpoint import (CheckpointCorrupt,
                                          checkpoint_trainer,
                                          restore_trainer)
    trainer.train(epochs=1)
    p = str(tmp_path / "ck.npz")
    checkpoint_trainer(trainer, p)
    other = _fresh_trainer(num_nodes=96, seed=3)
    with pytest.raises(CheckpointCorrupt, match="fingerprint"):
        restore_trainer(other, p)


def test_rotation_falls_back_on_corrupt_newest(trainer, tmp_path):
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=3)
    trainer.train(epochs=1)
    rot.save(trainer)
    trainer.train(epochs=1)
    rot.save(trainer)
    assert rot.existing() == [1, 2]
    _flip_byte(rot.path(2))
    t2 = _fresh_trainer()
    with _capture_events() as recs:
        assert rot.restore_latest(t2) == 1
    assert t2.epoch == 1
    assert any(r.get("kind") == "corrupt_fallback" for r in recs)


def test_rotation_only_if_ahead_never_rewinds_past_corrupt(trainer,
                                                           tmp_path):
    """only_if_ahead + a corrupt newest checkpoint: the fallback loop
    must STOP rather than restore an older checkpoint at/behind the
    live trainer (rewinding live progress is what the flag forbids)."""
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=3)
    for _ in range(3):
        trainer.train(epochs=1)
        rot.save(trainer)
    assert rot.existing() == [1, 2, 3]
    _flip_byte(rot.path(3))
    t2 = _fresh_trainer()
    t2.epoch = 2  # live progress equal to the best intact fallback
    assert rot.restore_latest(t2, only_if_ahead=True) is None
    assert t2.epoch == 2
    # without the flag the fallback still serves the newest intact one
    assert rot.restore_latest(t2) == 2


def test_rotation_save_refuses_poisoned_state(trainer, tmp_path):
    """check_params_finite guards EVERY checkpoint save (params AND
    opt state, one device sync): a poisoned state never persists."""
    import jax
    import jax.numpy as jnp
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2)
    trainer.train(epochs=1)
    done = [False]

    def poison(leaf):
        if not done[0]:
            done[0] = True
            return leaf.at[(0,) * leaf.ndim].set(jnp.nan)
        return leaf

    trainer.params = jax.tree_util.tree_map(poison, trainer.params)
    with pytest.raises(NumericFailure):
        rot.save(trainer)
    assert rot.existing() == []


def test_check_params_finite_covers_opt_state(trainer):
    import jax
    import jax.numpy as jnp
    from roc_tpu.utils.resilience import check_params_finite
    trainer.train(epochs=1)
    check_params_finite(trainer.params, trainer.opt_state)
    done = [False]

    def poison(leaf):
        if not done[0] and jnp.issubdtype(leaf.dtype, jnp.inexact):
            done[0] = True
            return leaf.at[(0,) * leaf.ndim].set(jnp.inf)
        return leaf

    bad_opt = jax.tree_util.tree_map(poison, trainer.opt_state)
    with pytest.raises(NumericFailure, match="opt_state"):
        check_params_finite(trainer.params, bad_opt)


# ---- watchdog deadline + preemption + fault-spec parsing ----

def test_heartbeat_deadline_raises_stallfailure():
    import time
    from roc_tpu.obs.heartbeat import Heartbeat, StallFailure
    t0 = time.monotonic()
    with pytest.raises(StallFailure):
        with Heartbeat("unit_stall", interval_s=0.05, deadline_s=0.3):
            time.sleep(30.0)
    assert time.monotonic() - t0 < 10.0


def test_heartbeat_no_deadline_stays_observational():
    import time
    from roc_tpu.obs.heartbeat import Heartbeat
    with Heartbeat("unit_fast", interval_s=0.05) as hb:
        time.sleep(0.12)
    assert hb.fired >= 1 and not hb.deadline_hit


def test_preemption_guard_graceful():
    from roc_tpu.resilience import preempt
    try:
        g = preempt.install(grace_s=5.0)
        assert not preempt.requested()
        os.kill(os.getpid(), signal.SIGTERM)
        assert preempt.requested()
        with pytest.raises(preempt.Preempted):
            preempt.raise_if_preempted(epoch=3)
    finally:
        preempt.reset()
    assert g.signum == signal.SIGTERM


def test_fault_spec_parse_and_arm_idempotent():
    from roc_tpu.resilience import inject
    try:
        s = inject.parse("sigkill:5")
        assert (s.site, s.epoch, s.proc) == ("sigkill", 5, None)
        s = inject.parse("nan_grads:3:1")
        assert s.proc == 1
        with pytest.raises(ValueError):
            inject.parse("bogus:1")
        with pytest.raises(ValueError):
            inject.parse("sigkill")
        inject.disarm()
        a = inject.arm("sigterm:4")
        a.fired = True
        # re-arming the identical spec keeps the spent record
        assert inject.arm("sigterm:4") is a
        assert inject.arm("sigterm:4").fired
        # a different spec replaces it
        assert not inject.arm("sigterm:5").fired
    finally:
        inject.disarm()


def test_recovery_adds_zero_new_compiled_programs(trainer, tmp_path):
    """Restore must reuse the compiled steps when shapes hold: a full
    poison->restore->replay cycle emits ZERO new compile-observer
    events (the acceptance gate for 'recovery adds zero new compiled
    programs')."""
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2)
    train_with_recovery(trainer, 2, rot, checkpoint_every=2)
    orig_train = trainer.train
    fails = {"n": 0}

    def flaky_train(epochs=None):
        hist = orig_train(epochs=epochs)
        if fails["n"] < 1:
            fails["n"] += 1
            hist[-1]["train_loss"] = float("nan")
        return hist

    trainer.train = flaky_train
    with _capture_events() as recs:
        train_with_recovery(trainer, 6, rot, checkpoint_every=2)
    assert trainer.epoch == 6
    compiles = [r for r in recs
                if r.get("cat") == "compile" and "lower_s" in r]
    assert not compiles, compiles


# ---- distributed: restore parity across a rebalance boundary ----

def test_distributed_restore_across_rebalance_boundary(tmp_path):
    """Checkpoint taken AFTER an epoch-boundary repartition, restored
    into a fresh trainer (which partitions from scratch): full-batch
    training is split-invariant, so the resumed run must match the
    uninterrupted never-repartitioned run <= 1e-5.  (The subprocess
    drill matrix covers crash-restart and elastic-P restores; this
    pins the PR-5 rebalance machinery composing with restore.)"""
    import jax
    from roc_tpu.parallel.distributed import DistributedTrainer
    ds = synthetic_dataset(96, 7, in_dim=12, num_classes=3, seed=7)
    cfg = TrainConfig(verbose=False, aggr_impl="ell", symmetric=True,
                      dropout_rate=0.0, eval_every=1 << 30)

    def mk():
        return DistributedTrainer(
            build_gcn([12, 8, 3], dropout_rate=0.0), ds, 2, cfg)

    ref = mk()
    ref.train(epochs=8)
    t1 = mk()
    t1.train(epochs=4)
    # force a repartition (move the split point by one node multiple)
    (l0, r0), (l1, r1) = [tuple(b) for b in t1.pg.bounds]
    t1._repartition([(l0, r0 - 8), (r0 - 7, r1)])
    t1.train(epochs=2)
    rot = CheckpointRotation(str(tmp_path / "ck"), keep=2)
    rot.save(t1)
    t2 = mk()
    assert rot.restore_latest(t2) == 6
    t2.train(epochs=2)
    for a, b in zip(jax.tree_util.tree_leaves(ref.params),
                    jax.tree_util.tree_leaves(t2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
