"""Observability subsystem tests (utils/profiling.py) + trainer hooks."""

import json
import os

import numpy as np
import pytest

from roc_tpu.utils.profiling import EpochTimer, MetricsLog, sync, trace


def test_epoch_timer_summary():
    t = EpochTimer(warmup=1)
    for ms in (100.0, 10.0, 12.0, 11.0):
        t.laps_ms.append(ms)
    s = t.summary()
    assert s["laps"] == 4
    assert s["warmup_ms"] == 100.0
    assert 10.0 <= s["median_ms"] <= 12.0
    assert s["min_ms"] == 10.0


def test_epoch_timer_lap_context():
    t = EpochTimer()
    with t.lap():
        pass
    assert len(t.laps_ms) == 1 and t.laps_ms[0] >= 0.0


def test_epoch_timer_phase_spans():
    t = EpochTimer()
    for ms in (10.0, 12.0, 11.0):
        t.spans_ms.setdefault("train", []).append(ms)
    t.spans_ms["eval"] = [5.0]
    with t.span("head_forward"):
        pass
    s = t.span_summary()
    assert set(s) == {"train", "eval", "head_forward"}
    assert s["train"]["n"] == 3
    assert 10.0 <= s["train"]["p50_ms"] <= 12.0
    assert s["train"]["p90_ms"] >= s["train"]["p50_ms"]
    assert s["eval"]["total_ms"] == 5.0
    assert s["head_forward"]["n"] == 1


def test_epoch_timer_span_syncs_on_device_array():
    import jax.numpy as jnp
    t = EpochTimer()
    with t.span("train", sync_on=jnp.ones((4,))):
        pass
    assert t.spans_ms["train"][0] >= 0.0
    # callable form: resolved at span EXIT, so it can barrier on work
    # dispatched inside the span
    produced = {}
    with t.span("dispatch", sync_on=lambda: produced["out"]):
        produced["out"] = jnp.ones((4,)) * 2
    assert len(t.spans_ms["dispatch"]) == 1


def test_epoch_timer_timeline_records_and_drain():
    """Span laps accumulate (name, mono_start, dur_ms) records for the
    timeline merger; take_timeline drains them."""
    t = EpochTimer()
    with t.span("train"):
        pass
    t.note_span("compile", 120.0)
    tl = t.take_timeline()
    assert [x[0] for x in tl] == ["train", "compile"]
    assert all(len(x) == 3 and x[1] > 0 and x[2] >= 0 for x in tl)
    # compile's start is back-derived from its duration
    assert tl[1][2] == 120.0
    assert t.take_timeline() == []   # drained
    # spans_ms got both laps too (the p50/p90 series is unchanged)
    assert set(t.spans_ms) == {"train", "compile"}


def test_epoch_timer_annotate_routes_through_trace_annotation():
    """annotate=True wraps each span in jax.profiler.TraceAnnotation
    (a no-op outside an active profiler session — but it must not
    break the span bookkeeping), so --profile-dir device traces carry
    the same phase names as the host timeline."""
    t = EpochTimer(annotate=True)
    with t.span("head_forward"):
        pass
    assert t.spans_ms["head_forward"][0] >= 0.0
    assert t.take_timeline()[0][0] == "head_forward"


def test_trainer_profile_dir_arms_span_annotation(tmp_path):
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer
    ds = synthetic_dataset(64, 6, in_dim=8, num_classes=3, seed=0)
    tr = Trainer(build_gcn([8, 8, 3]), ds,
                 TrainConfig(verbose=False, symmetric=True,
                             profile_dir=str(tmp_path / "prof")))
    assert tr.timer.annotate is True
    tr2 = Trainer(build_gcn([8, 8, 3]), ds,
                  TrainConfig(verbose=False, symmetric=True))
    assert tr2.timer.annotate is False


def test_sync_fetches():
    import jax.numpy as jnp
    sync({"a": jnp.ones((3, 3))})  # must not raise
    sync([])                        # empty pytree ok


def test_metrics_log_jsonl(tmp_path):
    p = str(tmp_path / "metrics.jsonl")
    log = MetricsLog(p)
    log.log({"epoch": 0, "train_loss": np.float32(1.5)})
    log.log({"epoch": 5, "train_loss": 1.2})
    log.close()
    lines = [json.loads(l) for l in open(p)]
    assert lines[0]["train_loss"] == 1.5
    assert log.last()["epoch"] == 5


def test_metrics_log_context_manager_closes(tmp_path):
    p = str(tmp_path / "m.jsonl")
    with MetricsLog(p) as log:
        log.log({"epoch": 0, "loss": 1.0})
        assert log._fh is not None
    assert log._fh is None
    # and on exception too
    log2 = MetricsLog(p)
    with pytest.raises(RuntimeError):
        with log2:
            log2.log({"epoch": 1})
            raise RuntimeError("boom")
    assert log2._fh is None
    assert len([json.loads(l) for l in open(p)]) == 2


def test_trainer_closes_metrics_log_on_exception(tmp_path):
    """Trainer.train must close the metrics handle even when the epoch
    loop dies mid-flight (the file-handle leak satellite)."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer

    ds = synthetic_dataset(64, 6, in_dim=8, num_classes=3, seed=0)
    p = str(tmp_path / "m.jsonl")
    cfg = TrainConfig(epochs=4, eval_every=1, verbose=False,
                      metrics_path=p, symmetric=True)
    tr = Trainer(build_gcn([8, 8, 3]), ds, cfg)
    tr.train(epochs=2)  # opens the handle via the first eval's log()

    def boom():
        raise RuntimeError("eval died")

    tr.evaluate = boom
    with pytest.raises(RuntimeError):
        tr.train(epochs=2)
    assert tr.metrics_log._fh is None


def test_trace_noop_without_dir():
    with trace(None):
        pass


def test_trainer_logs_metrics(tmp_path):
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer

    ds = synthetic_dataset(64, 6, in_dim=8, num_classes=3, seed=0)
    p = str(tmp_path / "m.jsonl")
    cfg = TrainConfig(epochs=6, eval_every=2, verbose=False,
                      metrics_path=p, symmetric=True)
    tr = Trainer(build_gcn([8, 8, 3]), ds, cfg)
    hist = tr.train()
    tr.metrics_log.close()
    assert len(hist) == 3
    recs = [json.loads(l) for l in open(p)]
    # evals land on eval_every - 1 phase so laps never include compile
    assert [r["epoch"] for r in recs] == [1, 3, 5]
    assert all("epoch_ms" in r and r["epoch_ms"] > 0 for r in recs)
    # the compile step is barriered and reported once, on the first eval
    assert "compile_ms" in recs[0] and recs[0]["compile_ms"] > 0
    assert all("compile_ms" not in r for r in recs[1:])
    # timer = 1 warmup (compile) lap + 3 steady laps
    s = tr.timer.summary()
    assert s["laps"] == 4
    assert s["warmup_ms"] == recs[0]["compile_ms"]
