"""Compile-cache prewarm correctness (ISSUE 7): `python -m
roc_tpu.prewarm` completes on CPU inside the CI budget, a warm second
process records ZERO new-program compile events (program_key set
equality against the auditor's enumeration AND no new step-program
cache entries) on both rig configs, a deliberately-stale cache
degrades gracefully (compile live, no crash), and the bench probe's
programspace preflight refuses growth against the cached warm state.
"""

import json
import os
import re
import shutil
import subprocess
import sys
import time

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "prewarm_worker.py")

# cache entries of STEP programs (the ones prewarm must cover); the
# epoch loop's eager scalar ops (decayed_lr's power/divide, metric
# summaries) legitimately compile tiny fresh entries in any process
_STEP_ENTRY = re.compile(
    r"jit__?(step|train_step|eval_step|tail_|head_|apply_update)")


def _env(cache_dir, events=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    env["ROC_TPU_CACHE_DIR"] = cache_dir
    env["ROC_TPU_CACHE_MIN_SECS"] = "0"
    if events:
        env["ROC_TPU_EVENTS"] = events
    else:
        env.pop("ROC_TPU_EVENTS", None)
    return env


@pytest.fixture(scope="module")
def warmed(tmp_path_factory):
    """One CLI prewarm of every rig config into a fresh cache — the
    acceptance-criterion invocation (must finish on CPU < 90 s),
    shared by the warm-process and stale-cache tests."""
    root = tmp_path_factory.mktemp("prewarm")
    cache = str(root / "cache")
    state = str(root / "warm_state.json")
    os.makedirs(cache)
    t0 = time.time()
    r = subprocess.run(
        [sys.executable, "-m", "roc_tpu.prewarm", "--config", "all",
         "--state", state],
        capture_output=True, text=True, timeout=90,
        env=_env(cache), cwd=_REPO)
    elapsed = time.time() - t0
    assert r.returncode == 0, r.stdout + r.stderr
    reports = [json.loads(line) for line in r.stdout.splitlines()
               if line.strip().startswith("{")]
    return {"cache": cache, "state": state, "root": str(root),
            "reports": {rep["config"]: rep for rep in reports},
            "elapsed": elapsed}


def test_prewarm_cli_reports_and_state(warmed):
    """The CLI's JSON report lines + warm-state artifact: every rig
    warmed, every program cold on a fresh cache, key sets recorded."""
    assert warmed["elapsed"] < 90.0
    reps = warmed["reports"]
    assert set(reps) == {"gin_flat8", "sgc_stream", "sgc_serve",
                         "sgc_serve_q8", "gin_mesh2d"}
    for name, rep in reps.items():
        assert rep["programs"] > 0
        assert rep["compile_cold"] == rep["programs"], name
        assert rep["compile_warm_hits"] == 0
        assert rep["failed"] == 0
    state = json.load(open(warmed["state"]))
    assert set(state) == {"gin_flat8", "sgc_stream", "sgc_serve",
                          "sgc_serve_q8", "gin_mesh2d"}
    for name in state:
        assert state[name]["programs"] == reps[name]["programs"]
        assert len(state[name]["keys"]) == reps[name]["programs"]
    assert os.listdir(warmed["cache"]), "cache stayed empty"


def test_second_prewarm_all_warm(warmed):
    """Idempotence: re-warming against the populated cache reports
    every program as a warm hit (file-based cold detection)."""
    r = subprocess.run(
        [sys.executable, "-m", "roc_tpu.prewarm", "--config",
         "sgc_stream", "--no-state"],
        capture_output=True, text=True, timeout=90,
        env=_env(warmed["cache"]), cwd=_REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    rep = next(json.loads(line) for line in r.stdout.splitlines()
               if line.strip().startswith("{"))
    assert rep["compile_cold"] == 0
    assert rep["compile_warm_hits"] == rep["programs"]


@pytest.mark.parametrize("name", ["gin_flat8", "sgc_stream"])
def test_warm_second_process_zero_new_programs(warmed, name):
    """THE acceptance criterion: a warm second process running the
    full live lifecycle (train+eval+predict) compiles ZERO new
    programs — its compile events' program_key set equals the
    auditor's enumeration exactly, and not one new STEP-program entry
    appears in the persistent cache (the eager epoch-loop scalars are
    the only permitted new entries)."""
    events = os.path.join(warmed["root"], f"ev_{name}.jsonl")
    before = set(os.listdir(warmed["cache"]))
    r = subprocess.run(
        [sys.executable, _WORKER, name],
        capture_output=True, text=True, timeout=240,
        env=_env(warmed["cache"], events=events), cwd=_REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "WORKER_OK" in r.stdout
    new = set(os.listdir(warmed["cache"])) - before
    new_steps = sorted(f for f in new if _STEP_ENTRY.search(f))
    assert not new_steps, (
        f"{name}: warm process compiled NEW step programs: "
        f"{new_steps}")
    live = {json.loads(line).get("program_key")
            for line in open(events)
            if '"cat": "compile"' in line}
    live.discard(None)
    from roc_tpu.analysis.programspace import (enumerate_programs,
                                               rig_configs)
    space = enumerate_programs(rig_configs()[name])
    assert live == space.observed_keys(), (
        f"{name}: live-only={sorted(live - space.observed_keys())} "
        f"static-only={sorted(space.observed_keys() - live)}")


def test_stale_cache_degrades_gracefully(warmed):
    """Corrupt every persisted executable: the live process must fall
    back to compiling fresh — no crash, training completes.  (The
    cache is an optimization; a stale/torn dir must never be fatal.)"""
    stale = os.path.join(warmed["root"], "stale_cache")
    shutil.copytree(warmed["cache"], stale)
    for f in os.listdir(stale):
        with open(os.path.join(stale, f), "wb") as fh:
            fh.write(b"\x00corrupt\xff" * 8)
    r = subprocess.run(
        [sys.executable, _WORKER, "sgc_stream"],
        capture_output=True, text=True, timeout=240,
        env=_env(stale), cwd=_REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "WORKER_OK" in r.stdout


def test_bench_preflight_refuses_growth(tmp_path, monkeypatch):
    """bench.py's programspace preflight: no warm state = no guard;
    unchanged key sets pass; a config whose program set GREW since
    the cached warm state is refused (the diff logic — the CLI
    enumeration itself is covered by test_programspace)."""
    import bench
    art = tmp_path / "art"
    art.mkdir()
    monkeypatch.setattr(bench, "_ART_DIR", str(art))
    payload = {"program_space": [
        {"config": "gin_flat8", "keys": ["a", "b", "c"]}]}

    class _R:
        stdout = json.dumps(payload)

    monkeypatch.setattr(bench.subprocess, "run",
                        lambda *a, **k: _R())
    # no warm state: nothing to guard
    assert bench._programspace_preflight() is None
    # unchanged: empty growth
    (art / "programspace_warm.json").write_text(json.dumps(
        {"gin_flat8": {"keys": ["a", "b", "c"], "programs": 3}}))
    assert bench._programspace_preflight() == {}
    # grown: one new key
    (art / "programspace_warm.json").write_text(json.dumps(
        {"gin_flat8": {"keys": ["a", "b"], "programs": 2}}))
    assert bench._programspace_preflight() == {"gin_flat8": 1}
    # a SHRUNK set is not growth (ratchet direction is free)
    (art / "programspace_warm.json").write_text(json.dumps(
        {"gin_flat8": {"keys": ["a", "b", "c", "d"], "programs": 4}}))
    assert bench._programspace_preflight() == {}
