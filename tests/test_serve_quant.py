"""Quantized serving tables (PR 19, ``roc_tpu/serve/quant.py``):

- round-trip identity: ``quantize∘dequantize∘quantize`` is bit-exact
  (per-row symmetric scaling maps the row max to ±Q), the property
  cold-load requantization and incremental refresh exactness rest on;
- params codec: ≥2-D float leaves quantize per-row with ``::scale``
  companions, 1-D leaves pass verbatim, and the codec round-trips;
- export→cold-load→query parity at int8: the cold-loaded predictor
  serves the export-process predictor's gated values bit-exactly and
  stays within the drift gate vs the fp32 trainer reference;
- ZERO new compiles on an int8 cold start (the test_serve acceptance,
  re-proven for the quantized program set in a child process);
- ``add_edges`` requantize-exactness: refreshing only the recomputed
  rows equals quantizing a full rebuild, codes and scales bit-equal;
- mid-rollout ``publish_quant``: a batch pinned to the fp32 version
  keeps serving fp32 bit-exactly after int8 publishes (quant-spec-
  pinned), and swapping back restores the original values;
- refusal paths: export REFUSES (no files written) past the drift
  thresholds, and an invalidation whose refreshed rows blow the
  pinned scale envelope refuses with the old version still published;
- fp8: byte-view persistence round-trips the dtype through npz, and
  export works behind explicitly relaxed thresholds (fp8-e4m3's 3
  mantissa bits intentionally fail the default gate).
"""

import json
import os
import re
import subprocess
import sys

import jax
import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "serve_worker.py")

_SERVE_ENTRY = re.compile(r"jit__serve_step")


def _dataset(V=300, seed=0):
    from roc_tpu.core.graph import synthetic_dataset
    return synthetic_dataset(num_nodes=V, avg_degree=6, in_dim=24,
                             num_classes=5, seed=seed)


def _sgc_model():
    from roc_tpu.models.sgc import build_sgc
    return build_sgc([24, 5], k=2, dropout_rate=0.5)


def _config(**kw):
    from roc_tpu.train.trainer import TrainConfig
    kw.setdefault("verbose", False)
    kw.setdefault("symmetric", True)
    return TrainConfig(**kw)


@pytest.fixture(scope="module")
def sgc_rig():
    from roc_tpu.train.trainer import Trainer
    ds = _dataset()
    tr = Trainer(_sgc_model(), ds, _config())
    tr.train(2)
    return ds, tr, np.asarray(jax.device_get(tr.predict()))


# ------------------------------------------------------------- codec

def test_roundtrip_identity_int8():
    """quantize∘dequantize∘quantize is bit-exact: the dequantized
    table requantizes to the SAME codes and scales (each row's max
    maps back to ±127 exactly), so a cold load reconstructs the
    device table bit-identically."""
    from roc_tpu.serve.quant import dequantize_rows, quantize_rows
    rng = np.random.RandomState(0)
    x = rng.randn(64, 24).astype(np.float32) * 5.0
    x[3] = 0.0                      # all-zero row: scale falls to 1.0
    q1, s1 = quantize_rows(x, "int8")
    assert q1.dtype == np.int8 and s1.dtype == np.float32
    assert float(s1[3]) == 1.0
    d = dequantize_rows(q1, s1)
    q2, s2 = quantize_rows(d, "int8")
    assert np.array_equal(q1, q2)
    assert np.array_equal(s1, s2)


def test_params_codec_roundtrip():
    """≥2-D float leaves quantize per-row with ``::scale``
    companions; 1-D leaves pass verbatim; decode inverts encode."""
    from roc_tpu.serve.quant import (PARAMS_SCALE_SUFFIX,
                                     dequantize_params,
                                     quantize_params)
    rng = np.random.RandomState(1)
    host = {"head/w": rng.randn(24, 5).astype(np.float32),
            "head/b": rng.randn(5).astype(np.float32)}
    store, roundtrip, qkeys = quantize_params(host, "int8")
    assert qkeys == ["head/w"]
    assert "head/w" + PARAMS_SCALE_SUFFIX in store
    assert np.array_equal(store["head/b"], host["head/b"])
    decoded = dequantize_params(store, "int8")
    assert sorted(decoded) == sorted(host)
    assert np.array_equal(decoded["head/w"], roundtrip["head/w"])
    assert np.array_equal(decoded["head/b"], host["head/b"])
    # round trip of the round trip is exact (the identity above)
    store2, roundtrip2, _ = quantize_params(
        {k: np.asarray(v) for k, v in roundtrip.items()}, "int8")
    assert np.array_equal(store2["head/w"], store["head/w"])


def test_fp8_storage_bytes_roundtrip(tmp_path):
    """fp8 codes persist as uint8 byte views (np.load loses the
    ml_dtypes dtype otherwise) and reconstruct bit-exactly through a
    real npz save/load."""
    from roc_tpu.serve.quant import (dequantize_rows, fp8_supported,
                                     from_storage_bytes,
                                     quantize_rows, to_storage_bytes)
    if not fp8_supported():
        pytest.skip("fp8-e4m3 unsupported in this stack")
    rng = np.random.RandomState(2)
    x = rng.randn(32, 16).astype(np.float32)
    q, s = quantize_rows(x, "fp8")
    p = str(tmp_path / "fp8.npz")
    np.savez(p, q=to_storage_bytes(q), s=s)
    z = np.load(p)
    q2 = from_storage_bytes(z["q"], "fp8")
    assert q2.dtype == q.dtype
    assert np.array_equal(q2.view(np.uint8), q.view(np.uint8))
    assert np.array_equal(dequantize_rows(q2, z["s"]),
                          dequantize_rows(q, s))


# ----------------------------------------------------- export / load

def test_export_cold_load_parity_int8(sgc_rig, tmp_path):
    """The tentpole acceptance: an int8 export passes the measured
    drift gate, records the table shrink in the manifest, and a cold
    load serves the gated values BIT-exactly (round-trip identity →
    identical device codes) with the same program keys."""
    from roc_tpu.serve.export import (build_predictor,
                                      export_predictor,
                                      load_predictor)
    ds, tr, ref = sgc_rig
    pred = build_predictor(_sgc_model(), ds, _config(),
                           params=tr.params, backend="precomputed",
                           quant="int8")
    art = str(tmp_path / "artifact")
    manifest = export_predictor(
        pred, art, dataset_meta={"V": ds.graph.num_nodes})
    qb = manifest["quant"]
    assert qb["spec"]["mode"] == "int8"
    assert qb["drift"]["ok"], qb["drift"]
    assert qb["table"]["shrink"] >= 3.0, qb["table"]
    ids = np.arange(ds.graph.num_nodes)
    want = np.asarray(pred.query(ids))
    cold = load_predictor(art)
    assert cold.quant == "int8"
    got = np.asarray(cold.query(ids))
    assert np.array_equal(got, want), (
        f"cold load drifted from the gated values by "
        f"{np.abs(got - want).max()}")
    assert cold.program_keys() == manifest["program_keys"]
    # and the served values stay within the gate vs the fp32 trainer
    rel = np.abs(got - ref).max() / max(1.0, np.abs(ref).max())
    assert rel <= qb["drift"]["dlogit_max"], rel


def test_cold_server_zero_new_compiles_int8(tmp_path):
    """The zero-new-compiles acceptance holds for the QUANTIZED
    program set: an int8 export in one child, a cold server in
    another — no new serve entry in the persistent cache, and the
    worker's compile events stay inside the manifest's keys."""
    cache = str(tmp_path / "cache")
    os.makedirs(cache)
    art = str(tmp_path / "artifact")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["ROC_TPU_CACHE_DIR"] = cache
    env["ROC_TPU_CACHE_MIN_SECS"] = "0"
    events = str(tmp_path / "events.jsonl")
    env["ROC_TPU_EVENTS"] = events
    code = (
        "import numpy as np, jax\n"
        "from roc_tpu.utils.compile_cache import enable_compile_cache\n"
        "enable_compile_cache()\n"
        "from roc_tpu.core.graph import synthetic_dataset\n"
        "from roc_tpu.models.sgc import build_sgc\n"
        "from roc_tpu.train.trainer import Trainer, TrainConfig\n"
        "from roc_tpu.serve.export import export_trainer\n"
        "ds = synthetic_dataset(num_nodes=300, avg_degree=6, "
        "in_dim=24, num_classes=5, seed=0)\n"
        "tr = Trainer(build_sgc([24, 5], k=2, dropout_rate=0.5), ds, "
        "TrainConfig(verbose=False, symmetric=True))\n"
        f"export_trainer(tr, ds, {art!r}, quant='int8')\n"
        "print('EXPORT_OK')\n")
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, timeout=240,
                       env=env, cwd=_REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "EXPORT_OK" in r.stdout
    before = set(os.listdir(cache))
    r = subprocess.run([sys.executable, _WORKER, art],
                       capture_output=True, text=True, timeout=240,
                       env=env, cwd=_REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "WORKER_OK" in r.stdout
    new = set(os.listdir(cache)) - before
    new_serve = sorted(f for f in new if _SERVE_ENTRY.search(f)
                       and f.endswith("-cache"))
    assert not new_serve, (
        f"cold int8 server compiled NEW serve programs: {new_serve}")
    man = json.load(open(os.path.join(art, "serve_manifest.json")))
    assert man["quant"]["spec"]["mode"] == "int8"
    assert any("_q8:" in k for k in man["program_keys"]), (
        man["program_keys"])
    live = {json.loads(line).get("program_key")
            for line in open(events)
            if '"cat": "compile"' in line}
    live.discard(None)
    serve_live = {k for k in live if k.startswith("serve_")}
    assert serve_live <= set(man["program_keys"]), (
        f"live-only serve keys: "
        f"{sorted(serve_live - set(man['program_keys']))}")


# --------------------------------------------------- refresh / swap

def test_add_edges_requantize_exactness(sgc_rig):
    """Incremental invalidation requantizes ONLY the recomputed rows
    — and lands bit-equal (codes AND scales) to quantizing a full
    rebuild of the mutated graph's tables."""
    from roc_tpu.core.graph import Graph
    from roc_tpu.serve.export import build_predictor
    from roc_tpu.serve.propagation import PropagationCache
    from roc_tpu.serve.quant import quantize_rows
    ds, tr, _ = sgc_rig
    pred = build_predictor(_sgc_model(), ds, _config(),
                           params=tr.params, backend="precomputed",
                           quant="int8")
    v0 = pred.published().version
    u, v = 3, 250
    n = pred.invalidate([u, v], [v, u])
    assert n > 0
    pub = pred.published()
    assert pub.version == v0 + 1 and pub.qmode == "int8"
    g2 = Graph(row_ptr=pred.cache.row_ptr.copy(),
               col_idx=pred.cache.col_idx.copy())
    rebuilt = PropagationCache.build(g2, pred.cache.ops,
                                     np.asarray(ds.features))
    # the host table is fp32 (exact invalidation layer)…
    assert np.abs(pred.cache.table - rebuilt.table).max() <= 1e-5
    # …and the device codes/scales equal a from-scratch quantization
    q_full, s_full = quantize_rows(
        np.asarray(pred.cache.table, dtype=np.float32), "int8")
    V = ds.graph.num_nodes
    assert np.array_equal(np.asarray(pub.table)[:V], q_full)
    assert np.array_equal(np.asarray(pub.scale)[:V], s_full)


def test_mid_rollout_publish_quant_bit_exact(sgc_rig):
    """``publish_quant('int8')`` mid-load: a dispatch pinned to the
    fp32 version keeps serving the fp32 program bit-exactly AFTER
    int8 publishes (quant-spec-pinned), new dispatches serve int8,
    and swapping back to fp32 restores the original values."""
    from roc_tpu.serve.export import build_predictor
    ds, tr, _ = sgc_rig
    pred = build_predictor(_sgc_model(), ds, _config(),
                           params=tr.params, backend="precomputed")
    assert pred.quant == "off"
    ids = np.arange(8).astype(np.int32)   # one padded bucket
    pub0 = pred.published()
    want_off = np.asarray(pred.query_device(ids, pub=pub0))
    v1 = pred.publish_quant("int8")
    pub1 = pred.published()
    assert pub1.qmode == "int8" and pub1.version == v1
    assert pred.quant == "int8"
    # the pinned fp32 batch is unaffected by the live int8 version
    again = np.asarray(pred.query_device(ids, pub=pub0))
    assert np.array_equal(again, want_off)
    # new dispatches serve the quantized program — close, not equal
    got_q8 = np.asarray(pred.query_device(ids, pub=pub1))
    rel = (np.abs(got_q8 - want_off).max()
           / max(1.0, np.abs(want_off).max()))
    assert 0.0 < rel <= 0.05, rel
    # rolling BACK re-publishes fp32 bit-exactly
    pred.publish_quant("off")
    back = np.asarray(pred.query_device(
        ids, pub=pred.published()))
    assert np.array_equal(back, want_off)


# ------------------------------------------------------- refusals

def test_export_refuses_on_drift(sgc_rig, tmp_path):
    """A quantization past the (here: impossible) thresholds REFUSES
    before any file is written — a drifting table never becomes an
    artifact."""
    from roc_tpu.serve.export import build_predictor, export_predictor
    from roc_tpu.serve.quant import QuantDriftError
    ds, tr, _ = sgc_rig
    pred = build_predictor(_sgc_model(), ds, _config(),
                           params=tr.params, backend="precomputed",
                           quant="int8")
    art = str(tmp_path / "refused")
    with pytest.raises(QuantDriftError) as ei:
        export_predictor(pred, art, drift_dlogit_max=1e-12)
    assert "drift" in str(ei.value)
    assert not os.path.exists(art), "refusal must precede any write"


def test_invalidate_scale_guard_refuses(sgc_rig):
    """Refreshed rows whose scale blows the envelope pinned at gate
    time refuse (QuantDriftError) — and the OLD version stays
    published, still serving."""
    from roc_tpu.serve.export import build_predictor
    from roc_tpu.serve.quant import QuantDriftError
    ds, tr, _ = sgc_rig
    pred = build_predictor(_sgc_model(), ds, _config(),
                           params=tr.params, backend="precomputed",
                           quant="int8")
    pub0 = pred.published()
    want = np.asarray(pred.query(np.arange(8)))
    pred._scale_guard = 1e-12     # simulate a poisoned envelope
    with pytest.raises(QuantDriftError):
        pred.invalidate([3, 250], [250, 3])
    assert pred.published().version == pub0.version
    assert np.array_equal(np.asarray(pred.query(np.arange(8))), want)


def test_fp8_export_behind_relaxed_gate(sgc_rig, tmp_path):
    """fp8-e4m3 (3 mantissa bits) drifts genuinely more than int8 —
    exporting it requires DELIBERATELY relaxed thresholds, and then
    cold-load parity holds exactly like int8."""
    from roc_tpu.serve.export import (build_predictor,
                                      export_predictor,
                                      load_predictor)
    from roc_tpu.serve.quant import fp8_supported
    if not fp8_supported():
        pytest.skip("fp8-e4m3 unsupported in this stack")
    ds, tr, _ = sgc_rig
    pred = build_predictor(_sgc_model(), ds, _config(),
                           params=tr.params, backend="precomputed",
                           quant="fp8")
    art = str(tmp_path / "fp8_art")
    manifest = export_predictor(
        pred, art, drift_argmax_min=0.90, drift_dlogit_max=0.20)
    assert manifest["quant"]["spec"]["mode"] == "fp8"
    ids = np.arange(ds.graph.num_nodes)
    want = np.asarray(pred.query(ids))
    cold = load_predictor(art)
    assert cold.quant == "fp8"
    assert np.array_equal(np.asarray(cold.query(ids)), want)
