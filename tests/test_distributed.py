"""Sharded training on an 8-virtual-device CPU mesh: partition-count
invariance (1 vs N shards must match single-device numerics), halo
exchange correctness, psum'd metrics."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu.core.graph import synthetic_dataset
from roc_tpu.core.partition import partition_graph
from roc_tpu.models.gcn import build_gcn
from roc_tpu.parallel.distributed import (DistributedTrainer, make_mesh,
                                          pad_nodes, remap_to_padded,
                                          unpad_nodes)
from roc_tpu.train.trainer import TrainConfig, Trainer


@pytest.fixture(scope="module")
def dataset():
    return synthetic_dataset(96, 7, in_dim=12, num_classes=3, seed=11)


def _no_dropout_cfg(**kw):
    return TrainConfig(dropout_rate=0.0, verbose=False, epochs=8,
                       weight_decay=1e-3, learning_rate=0.01, **kw)


def test_remap_roundtrip(dataset):
    pg = partition_graph(dataset.graph, 4, node_multiple=8,
                         edge_multiple=32)
    col_padded = remap_to_padded(pg)
    l2g = pg.local_to_global().reshape(-1)  # padded coord -> global id
    # every real edge must map back to its original global src
    for p in range(4):
        e = int(pg.real_edges[p])
        back = l2g[col_padded[p, :e]]
        np.testing.assert_array_equal(back, pg.part_col_idx[p, :e])
        assert (col_padded[p, e:] == pg.num_parts * pg.part_nodes).all()


def test_pad_unpad_roundtrip(dataset):
    pg = partition_graph(dataset.graph, 4, node_multiple=8)
    padded = pad_nodes(dataset.features, pg)
    back = unpad_nodes(padded, pg)
    np.testing.assert_array_equal(back, dataset.features)


@pytest.mark.parametrize("num_parts", [2, 4, 8])
def test_distributed_matches_single_device(dataset, num_parts):
    """Same init, same data, no dropout: the sharded step must reproduce
    single-device training (the reference's partition-count invariance)."""
    model = build_gcn([dataset.in_dim, 16, dataset.num_classes],
                      dropout_rate=0.0)
    cfg = _no_dropout_cfg()
    single = Trainer(model, dataset, cfg)
    dist = DistributedTrainer(model, dataset, num_parts, cfg)
    # identical initial params by construction (same seed)
    for k in single.params:
        np.testing.assert_array_equal(np.asarray(single.params[k]),
                                      np.asarray(dist.params[k]))
    single.train()
    dist.train()
    for k in single.params:
        np.testing.assert_allclose(np.asarray(single.params[k]),
                                   np.asarray(dist.params[k]),
                                   rtol=2e-4, atol=2e-5)
    m_s = single.evaluate()
    m_d = dist.evaluate()
    assert m_s["train_cnt"] == m_d["train_cnt"]
    assert m_s["val_cnt"] == m_d["val_cnt"]
    assert m_s["test_cnt"] == m_d["test_cnt"]
    assert abs(m_s["test_acc"] - m_d["test_acc"]) < 0.02
    np.testing.assert_allclose(m_s["train_loss"], m_d["train_loss"],
                               rtol=1e-3)


def test_distributed_lerp_families_match_single(dataset):
    """APPNP and GCNII (the fixed-scalar lerp families) reproduce
    their single-device trajectories under the 4-part sharded step —
    lerp composes with the halo/psum machinery like any elementwise
    op, but nothing else exercises it multi-part with real training."""
    from roc_tpu.models.appnp import build_appnp
    from roc_tpu.models.gcn2 import build_gcn2
    builds = (
        lambda: build_appnp([dataset.in_dim, 16, dataset.num_classes],
                            k=3, alpha=0.2, dropout_rate=0.0),
        lambda: build_gcn2([dataset.in_dim, 16, 16,
                            dataset.num_classes], dropout_rate=0.0),
    )
    for build in builds:
        model = build()
        cfg = _no_dropout_cfg()
        single = Trainer(model, dataset, cfg)
        dist = DistributedTrainer(model, dataset, 4, cfg)
        single.train()
        dist.train()
        for k in single.params:
            np.testing.assert_allclose(np.asarray(single.params[k]),
                                       np.asarray(dist.params[k]),
                                       rtol=2e-4, atol=2e-5)
    # the O(V/P)-memory ring halo composes with lerp too (additive
    # aggregation only — attention rejects it, these must not)
    ring = DistributedTrainer(builds[0](), dataset, 4,
                              _no_dropout_cfg(halo="ring"))
    ring.train()
    base = Trainer(builds[0](), dataset, _no_dropout_cfg())
    base.train()
    np.testing.assert_allclose(ring.evaluate()["train_loss"],
                               base.evaluate()["train_loss"],
                               rtol=1e-3)


def test_distributed_blocked_impl(dataset):
    """blocked aggregation under shard_map matches segment."""
    model = build_gcn([dataset.in_dim, 16, dataset.num_classes],
                      dropout_rate=0.0)
    outs = {}
    for impl in ("segment", "blocked", "ell"):
        cfg = _no_dropout_cfg(aggr_impl=impl, chunk=64)
        t = DistributedTrainer(model, dataset, 4, cfg)
        t.train(epochs=3)
        outs[impl] = t.evaluate()
    np.testing.assert_allclose(outs["segment"]["train_loss"],
                               outs["blocked"]["train_loss"], rtol=1e-3)
    np.testing.assert_allclose(outs["segment"]["train_loss"],
                               outs["ell"]["train_loss"], rtol=1e-3)


def test_distributed_converges(dataset):
    model = build_gcn([dataset.in_dim, 24, dataset.num_classes],
                      dropout_rate=0.1)
    cfg = TrainConfig(dropout_rate=0.1, verbose=False, epochs=50,
                      weight_decay=1e-4, learning_rate=0.01)
    t = DistributedTrainer(model, dataset, 8, cfg)
    t.train()
    m = t.evaluate()
    assert m["train_acc"] > 0.9
    assert m["test_acc"] > 0.6


@pytest.mark.parametrize("num_parts", [2, 4, 8])
def test_ring_halo_matches_gather(dataset, num_parts):
    """halo='ring' (ppermute rotation, O(V/P) memory) must reproduce the
    one-shot all_gather numerics exactly."""
    model = build_gcn([dataset.in_dim, 16, dataset.num_classes],
                      dropout_rate=0.0)
    res = {}
    for halo in ("gather", "ring"):
        cfg = _no_dropout_cfg(halo=halo)
        t = DistributedTrainer(model, dataset, num_parts, cfg)
        t.train(epochs=5)
        res[halo] = t
    for k in res["gather"].params:
        np.testing.assert_allclose(
            np.asarray(res["gather"].params[k]),
            np.asarray(res["ring"].params[k]), rtol=2e-4, atol=2e-5)
    m_g, m_r = res["gather"].evaluate(), res["ring"].evaluate()
    np.testing.assert_allclose(m_g["train_loss"], m_r["train_loss"],
                               rtol=1e-3)


@pytest.mark.parametrize("num_parts", [2, 4])
@pytest.mark.parametrize("use_weights", [False, True])
def test_ring_overlap_matches_sequential(dataset, num_parts,
                                         use_weights):
    """The double-buffered hop schedule (ppermute issued before the
    scatter-accumulate) must reproduce the strictly sequential form:
    fwd + grad <= 1e-5 fp32, with and without the fused-weight
    epilogue — the rotation never reads the accumulator, so the
    reorder is a schedule change, not a numerics one."""
    from jax.sharding import PartitionSpec as P
    from roc_tpu.ops.norm import inv_sqrt_degree_np
    from roc_tpu.parallel import ring as R
    from roc_tpu.parallel.distributed import _shard_map
    pg = partition_graph(dataset.graph, num_parts, node_multiple=8)
    rt = R.build_ring_tables(pg)
    mesh = make_mesh(num_parts)
    rng = np.random.RandomState(7)
    xs = jnp.asarray(pad_nodes(
        rng.randn(dataset.graph.num_nodes, 8).astype(np.float32), pg))
    src, dst = jnp.asarray(rt.src), jnp.asarray(rt.dst)
    w = jnp.asarray(R.ring_weight_tables(
        pg, rt, inv_sqrt_degree_np(dataset.graph.in_degree)))
    res = {}
    for overlap in (False, True):
        def body(xb, sb, db, wb, o=overlap):
            f = lambda xx: R.ring_aggregate(
                xx[0], sb[0], db[0],
                weights=wb[0] if use_weights else None,
                overlap=o)[None]
            g = jax.grad(lambda xx: jnp.sum(f(xx) ** 2))(xb)
            return f(xb), g
        sm = jax.jit(_shard_map(body, mesh, (P("parts"),) * 4,
                                (P("parts"), P("parts"))))
        out, grad = sm(xs, src, dst, w)
        res[overlap] = (np.asarray(out), np.asarray(grad))
    np.testing.assert_allclose(res[True][0], res[False][0],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(res[True][1], res[False][1],
                               rtol=1e-5, atol=1e-5)


def test_ring_overlap_config_trains_identically(dataset):
    """TrainConfig.ring_overlap=False (the sequential measurement
    reference) reaches the same parameters as the default overlapped
    schedule through a real distributed training run."""
    model = build_gcn([dataset.in_dim, 16, dataset.num_classes],
                      dropout_rate=0.0)
    res = {}
    for overlap in (True, False):
        cfg = _no_dropout_cfg(halo="ring", ring_overlap=overlap)
        t = DistributedTrainer(model, dataset, 4, cfg)
        t.train(epochs=3)
        res[overlap] = t
    for k in res[True].params:
        np.testing.assert_allclose(np.asarray(res[True].params[k]),
                                   np.asarray(res[False].params[k]),
                                   rtol=1e-5, atol=1e-6)


def test_ring_tables_cover_all_edges(dataset):
    """Every global edge appears in exactly one (partition, shard) table,
    reconstructed back to its (global_src, global_dst) pair."""
    from roc_tpu.parallel.ring import build_ring_tables
    pg = partition_graph(dataset.graph, 4, node_multiple=8)
    rt = build_ring_tables(pg)
    P = pg.num_parts
    starts = np.asarray([l for l, _ in pg.bounds], dtype=np.int64)
    got = []
    for p in range(P):
        for s in range(P):
            real = rt.src[p, s] != pg.part_nodes  # dummy src marks padding
            gsrc = rt.src[p, s][real].astype(np.int64) + starts[s]
            gdst = rt.dst[p, s][real].astype(np.int64) + starts[p]
            got.append(np.stack([gsrc, gdst], axis=1))
    got = np.concatenate(got, axis=0)
    assert got.shape[0] == dataset.graph.num_edges
    # reference edge list from the global CSR
    g = dataset.graph
    dst = np.repeat(np.arange(g.num_nodes, dtype=np.int64),
                    np.diff(g.row_ptr.astype(np.int64)))
    ref = np.stack([g.col_idx.astype(np.int64), dst], axis=1)
    order = np.lexsort((got[:, 0], got[:, 1]))
    ref_order = np.lexsort((ref[:, 0], ref[:, 1]))
    np.testing.assert_array_equal(got[order], ref[ref_order])


def test_ring_padding_ratio_bounded():
    """P=8 power-law graph: SPMD padding must stay moderate (the
    module docstring claims ~1.5-1.7x for edge-balanced partitions;
    the exact value is a property of the fixture draw — 2.05 on the
    current generator stream — so the bound guards against runaway
    padding, not a point estimate)."""
    from roc_tpu.parallel.ring import build_ring_tables
    ds = synthetic_dataset(512, 9, in_dim=8, num_classes=4, seed=3)
    pg = partition_graph(ds.graph, 8, node_multiple=8)
    rt = build_ring_tables(pg)
    assert rt.padding_ratio >= 1.0
    assert rt.padding_ratio < 2.5, (
        f"ring padding ratio {rt.padding_ratio:.2f} exceeds the bound")


def test_sectioned_distributed_matches_single(dataset):
    """aggr_impl='sectioned' under shard_map (uniform per-part chunk
    plans) must reproduce the single-device sectioned results."""
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig, Trainer

    ds = dataset
    kw = dict(learning_rate=0.05, epochs=3, eval_every=1 << 30,
              verbose=False, symmetric=True, aggr_impl="sectioned")
    t1 = Trainer(build_gcn([ds.in_dim, 8, ds.num_classes],
                           dropout_rate=0.0), ds, TrainConfig(**kw))
    t1.train()
    t4 = DistributedTrainer(build_gcn([ds.in_dim, 8, ds.num_classes],
                                      dropout_rate=0.0), ds, 4,
                            TrainConfig(**kw))
    t4.train(epochs=3)
    for k in t1.params:
        np.testing.assert_allclose(np.asarray(t1.params[k]),
                                   np.asarray(t4.params[k]),
                                   rtol=2e-4, atol=2e-4)
    m1, m4 = t1.evaluate(), t4.evaluate()
    assert abs(m1["train_loss"] - m4["train_loss"]) < 1e-2


def test_sectioned_distributed_multi_section(dataset):
    """Multi-section, multi-chunk plan (section_rows=16 forces ~24
    sections over 4 parts): tables must match the single-device
    sectioned aggregation exactly."""
    import jax.numpy as jnp
    from roc_tpu.core.ell import sectioned_from_graph
    from roc_tpu.ops.aggregate import aggregate_ell_sect, aggregate_segment
    from roc_tpu.core.partition import padded_edge_list
    ds = dataset
    g = ds.graph
    F = 6
    feats = np.random.RandomState(2).rand(g.num_nodes + 1, F).astype(
        np.float32)
    feats[-1] = 0
    x = jnp.asarray(feats)
    src, dst = padded_edge_list(g, multiple=64)
    want = aggregate_segment(x, jnp.asarray(src), jnp.asarray(dst),
                             g.num_nodes)
    sect = sectioned_from_graph(g.row_ptr, g.col_idx, g.num_nodes,
                                section_rows=16, seg_rows=32)
    assert len(sect.idx) > 2  # genuinely multi-section
    sidx, sdst, meta = sect.as_jax()
    got = aggregate_ell_sect(x, sidx, sdst, meta, g.num_nodes)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)
    # distributed: same forced sectioning through shard_dataset, with
    # per-part padded-chunk uniformity (parts have unequal edge counts)
    from roc_tpu.parallel import multihost as mh
    from roc_tpu.parallel.distributed import shard_dataset
    from roc_tpu.core.partition import partition_graph
    mesh = mh.make_parts_mesh(4)
    pg = partition_graph(g, 4, edge_multiple=64)
    want_sd = shard_dataset(ds, pg, mesh, aggr_impl="sectioned",
                            section_rows=32)
    got_sd = mh.shard_dataset_local(ds, pg, mesh,
                                    aggr_impl="sectioned",
                                    section_rows=32)
    assert len(want_sd.sect_idx) > 2
    assert got_sd.sect_meta == want_sd.sect_meta
    for a, b in zip(got_sd.sect_idx, want_sd.sect_idx):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(got_sd.sect_sub_dst, want_sd.sect_sub_dst):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sectioned_distributed_honors_sub_w_and_u16(dataset):
    """TrainConfig.sect_sub_w / sect_u16 must shape the DISTRIBUTED
    sectioned tables too (round-4 advisor: they were silently ignored
    by DistributedTrainer), and training must still match the
    single-device path numerically."""
    ds = dataset
    kw = dict(learning_rate=0.05, epochs=2, eval_every=1 << 30,
              verbose=False, symmetric=True, aggr_impl="sectioned",
              sect_sub_w=16, sect_u16=True)
    t1 = Trainer(build_gcn([ds.in_dim, 8, ds.num_classes],
                           dropout_rate=0.0), ds, TrainConfig(**kw))
    t4 = DistributedTrainer(build_gcn([ds.in_dim, 8, ds.num_classes],
                                      dropout_rate=0.0), ds, 4,
                            TrainConfig(**kw))
    # the knobs actually shaped the uploaded tables
    for a in t4.data.sect_idx:
        assert a.shape[-1] == 16
        assert a.dtype == jnp.uint16
    t1.train()
    t4.train(epochs=2)
    for k in t1.params:
        np.testing.assert_allclose(np.asarray(t1.params[k]),
                                   np.asarray(t4.params[k]),
                                   rtol=2e-4, atol=2e-4)
