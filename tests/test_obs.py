"""Observability subsystem (roc_tpu/obs): event bus, run manifest,
compile observer, stall heartbeats, report CLI, and the stdout-print
lint ratchet."""

import json
import os
import subprocess
import sys
import time

import pytest

from roc_tpu.obs.events import ConsoleSink, EventLog, JsonlSink
from roc_tpu.obs.heartbeat import Heartbeat

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------- event bus

def test_jsonl_event_roundtrip(tmp_path):
    p = str(tmp_path / "events.jsonl")
    bus = EventLog([JsonlSink(p)])
    bus.emit("resolve", "picked sectioned", requested="auto",
             resolved="sectioned")
    bus.emit("epoch", "epoch 5", console=False, epoch=5,
             epoch_ms=12.5)
    bus.close()
    recs = [json.loads(line) for line in open(p)]
    assert [r["cat"] for r in recs] == ["resolve", "epoch"]
    assert recs[0]["resolved"] == "sectioned"
    assert recs[1]["epoch_ms"] == 12.5
    # the console gate is sink routing, not payload
    assert "console" not in recs[1]
    assert all("t" in r and "msg" in r for r in recs)


def test_console_sink_preserves_hash_prefix(capsys):
    bus = EventLog([ConsoleSink()])
    bus.emit("plan", "memory plan: halo=gather")
    bus.emit("plan", "hidden", console=False)
    err = capsys.readouterr().err
    assert "# memory plan: halo=gather" in err
    assert "hidden" not in err


def test_sink_failure_never_raises(capsys):
    class Boom:
        def write(self, rec):
            raise RuntimeError("disk full")

        def close(self):
            pass

    bus = EventLog([Boom()])
    bus.emit("run", "a")  # must not raise
    bus.emit("run", "b")
    assert "sink" in capsys.readouterr().err  # one-time note


def test_jsonable_fields_degrade_to_str(tmp_path):
    import numpy as np
    p = str(tmp_path / "e.jsonl")
    bus = EventLog([JsonlSink(p)])
    bus.emit("plan", "x", arr=np.arange(3), big=np.int64(7),
             obj=object())
    bus.close()
    rec = json.loads(open(p).read())
    assert rec["arr"] == [0, 1, 2]
    assert rec["big"] == 7
    assert isinstance(rec["obj"], str)


# ------------------------------------------------------- run manifest

def test_run_manifest_schema(tmp_path):
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.obs.events import configure
    from roc_tpu.obs.manifest import run_manifest
    from roc_tpu.train.trainer import TrainConfig
    p = str(tmp_path / "ev.jsonl")
    try:
        configure(jsonl_path=p, console=False)
        ds = synthetic_dataset(64, 4, in_dim=8, num_classes=3, seed=0)
        fields = run_manifest(config=TrainConfig(aggr_impl="ell"),
                              dataset=ds,
                              model=build_gcn([8, 8, 3]),
                              console=False)
    finally:
        configure(jsonl_path=None)
    rec = json.loads(open(p).read())
    assert rec["cat"] == "manifest"
    for key in ("jax_version", "platform", "device_count", "config",
                "resolved", "dataset", "model"):
        assert key in rec, key
    assert rec["resolved"]["aggr_impl"] == "ell"
    assert rec["dataset"]["num_nodes"] == 64
    assert rec["config"]["aggr_impl"] == "ell"
    # dtypes serialize by dtype NAME
    assert rec["config"]["dtype"] == "float32"
    assert fields["dataset"]["num_edges"] == ds.graph.num_edges


def test_git_sha_resolves_here():
    from roc_tpu.obs.manifest import git_sha
    sha = git_sha()
    assert sha is None or (len(sha) == 40
                           and all(c in "0123456789abcdef" for c in sha))


# ---------------------------------------------------------- heartbeat

def test_heartbeat_fire_and_cancel(tmp_path):
    p = str(tmp_path / "hb.jsonl")
    bus = EventLog([JsonlSink(p)])
    with Heartbeat("claiming backend", interval_s=0.05, bus=bus) as hb:
        time.sleep(0.22)
    fired_at_exit = hb.fired
    assert fired_at_exit >= 2
    time.sleep(0.15)  # canceled: no further beats
    assert hb.fired == fired_at_exit
    recs = [json.loads(line) for line in open(p)]
    assert all(r["cat"] == "stall" for r in recs)
    assert all(r["stage"] == "claiming backend" for r in recs)
    assert "still waiting in claiming backend" in recs[0]["msg"]
    assert recs[-1]["elapsed_s"] >= recs[0]["elapsed_s"]


def test_heartbeat_fast_region_emits_nothing(tmp_path):
    p = str(tmp_path / "hb.jsonl")
    bus = EventLog([JsonlSink(p)])
    with Heartbeat("quick", interval_s=5.0, bus=bus) as hb:
        pass
    assert hb.fired == 0
    assert not os.path.exists(p)  # lazy sink never opened


def test_heartbeat_zero_interval_is_disabled(tmp_path):
    """ROC_TPU_HEARTBEAT_S=0 is the off switch — no watchdog thread,
    never a zero-wait spin flooding the artifact."""
    p = str(tmp_path / "hb.jsonl")
    bus = EventLog([JsonlSink(p)])
    with Heartbeat("off", interval_s=0, bus=bus) as hb:
        time.sleep(0.05)
    assert hb.fired == 0 and hb._thread is None
    assert not os.path.exists(p)


# ----------------------------------------------------- compile observer

def test_cost_and_memory_summary_degrade_gracefully():
    from roc_tpu.obs.compile_watch import cost_summary, memory_summary

    class NoIntrospection:
        def cost_analysis(self):
            raise NotImplementedError("backend says no")

        def memory_analysis(self):
            return None

    c = cost_summary(NoIntrospection())
    assert c == {"flops": None, "bytes_accessed": None}
    m = memory_summary(NoIntrospection())
    assert m["peak_bytes"] is None


def test_observed_jit_degrades_to_plain_call(tmp_path):
    """A wrapped callable without the AOT surface must still execute
    (one degradation event, then plain calls)."""
    from roc_tpu.obs.compile_watch import ObservedJit
    calls = []

    def plain(x):
        calls.append(x)
        return x + 1

    oj = ObservedJit(jitfn=plain, name="stub")
    assert oj(1) == 2 and oj(2) == 3
    assert calls == [1, 2]
    assert oj._degraded and oj.cost is None


def test_observed_jit_captures_cost_and_model_delta():
    import jax.numpy as jnp
    from roc_tpu.obs.compile_watch import ObservedJit

    oj = ObservedJit(lambda x: (x @ x).sum(), name="mm",
                     modeled_bytes=1)
    x = jnp.ones((32, 32))
    assert float(oj(x)) == float((x @ x).sum())
    assert oj.cost is not None
    assert oj.cost["flops"] and oj.cost["flops"] > 0
    assert oj.cost["compile_s"] >= 0
    # CPU exposes memory_analysis -> the modeled-vs-actual delta exists
    assert oj.cost["peak_bytes"] is not None
    assert oj.cost["model_delta_bytes"] == oj.cost["peak_bytes"] - 1
    # steady-state path reuses the compiled executable
    assert oj._compiled is not None
    assert float(oj(x + 1)) > 0


def test_peak_flops_table():
    from roc_tpu.obs.compile_watch import peak_flops_per_s
    assert peak_flops_per_s("TPU v5 lite") == 197e12
    assert peak_flops_per_s("TPU v4") == 275e12
    assert peak_flops_per_s("cpu") is None


# --------------------------------------------- end-to-end through CLI

def test_cli_events_jsonl_and_report(tmp_path):
    """The acceptance path: a CPU CLI run with --events produces a
    manifest, a compile event with flops/peak-HBM/modeled-delta, and
    per-phase epoch spans; `python -m roc_tpu.report` renders it."""
    from roc_tpu.obs.events import configure
    from roc_tpu.train import cli
    ev = str(tmp_path / "events.jsonl")
    old_env = os.environ.get("ROC_TPU_EVENTS")
    try:
        rc = cli.main(["--cpu", "--no-compile-cache", "-e", "4",
                       "-layers", "8-8-3", "--impl", "ell",
                       "--eval-every", "2", "--events", ev])
    finally:
        configure(jsonl_path=None)
        if old_env is None:
            os.environ.pop("ROC_TPU_EVENTS", None)
        else:
            os.environ["ROC_TPU_EVENTS"] = old_env
    assert rc == 0
    recs = [json.loads(line) for line in open(ev)]
    cats = {r["cat"] for r in recs}
    assert {"manifest", "compile", "epoch", "run"} <= cats
    comp = [r for r in recs if r["cat"] == "compile"
            and r.get("name") == "train_step"]
    assert comp, recs
    assert comp[0]["flops"] > 0
    assert comp[0]["peak_bytes"] > 0
    assert comp[0]["modeled_bytes"] > 0
    assert comp[0]["model_delta_bytes"] == \
        comp[0]["peak_bytes"] - comp[0]["modeled_bytes"]
    spans = [r for r in recs if r["cat"] == "epoch" and r.get("spans")]
    assert spans and {"compile", "train", "eval"} <= \
        set(spans[-1]["spans"])
    ep = [r for r in recs if r["cat"] == "epoch" and "epoch_ms" in r]
    assert ep and ep[0]["edges_per_s"] > 0

    r = subprocess.run(
        [sys.executable, "-m", "roc_tpu.report", ev],
        capture_output=True, text=True, cwd=_REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120)
    assert r.returncode == 0, r.stderr
    for needle in ("run manifest", "compile", "train_step",
                   "phase spans", "edges_per_s"):
        assert needle in r.stdout, (needle, r.stdout)


# --------------------------------------------------- bench heartbeats

def test_bench_slow_stage_emits_heartbeat_before_timeout(
        tmp_path, monkeypatch):
    """A forced-slow bench stage must leave stall events (parent-side
    'bench:<stage>' heartbeats) before its timeout — never again a
    blank 'timeout after Ns' with zero evidence."""
    sys.path.insert(0, _REPO)
    import bench
    from roc_tpu.obs.events import configure
    ev = str(tmp_path / "events.jsonl")
    monkeypatch.setenv("ROC_TPU_BENCH_ARTIFACTS", str(tmp_path))
    monkeypatch.setenv("ROC_TPU_HEARTBEAT_S", "0.5")
    # a FRESH compile-cache dir: a warm persistent cache (left by any
    # earlier bench/test run in this container) lets the child finish
    # inside the 2 s budget on a fast box, voiding the forced-slow
    # premise — the stage must pay its cold compile here
    monkeypatch.setenv("ROC_TPU_CACHE_DIR", str(tmp_path / "cache"))
    monkeypatch.setattr(bench, "_ART_DIR", str(tmp_path))
    monkeypatch.setattr(bench, "_STAGES_PATH",
                        str(tmp_path / "bench_stages.jsonl"))
    try:
        configure(jsonl_path=ev, console=False)
        # 'full' at CPU with a 2 s timeout: the child cannot even
        # finish importing jax — a guaranteed slow stage
        rec = bench._run_stage(
            "full", 2.0,
            ["--cpu", "--nodes", "4096", "--edges", "32768",
             "--epochs", "1"], grace=5.0)
    finally:
        configure(jsonl_path=None)
    assert not rec.get("ok")
    assert "timeout" in rec.get("error", "")
    assert rec.get("heartbeats", 0) >= 1
    stalls = [json.loads(line) for line in open(ev)
              if json.loads(line).get("cat") == "stall"]
    assert stalls
    assert stalls[0]["stage"] == "bench:full"
    assert "still waiting in bench:full" in stalls[0]["msg"]


# ------------------------------------------------------- lint ratchet

def test_lint_prints_ratchet_passes():
    """scripts/lint_prints.sh: the event-log migration cannot regress
    — a bare stdout print() in roc_tpu/ fails the tier."""
    r = subprocess.run(
        ["sh", os.path.join(_REPO, "scripts", "lint_prints.sh")],
        capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stdout + r.stderr


def test_lint_prints_catches_stdout_leak(tmp_path):
    """The ratchet actually bites: a planted bare print() is caught."""
    import shutil
    victim = os.path.join(_REPO, "roc_tpu", "obs", "__init__.py")
    planted = tmp_path / "repo"
    (planted / "scripts").mkdir(parents=True)
    shutil.copy(os.path.join(_REPO, "scripts", "lint_prints.sh"),
                planted / "scripts" / "lint_prints.sh")
    dst = planted / "roc_tpu"
    dst.mkdir()
    (dst / "leaky.py").write_text("print('oops stdout')\n")
    # the planted tree has no roc_tpu.analysis package — the thin
    # wrapper imports the linter from the real checkout via PYTHONPATH
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(["sh", str(planted / "scripts" /
                                  "lint_prints.sh")],
                       capture_output=True, text=True, timeout=60,
                       env=env)
    assert r.returncode == 1
    assert "leaky.py:1" in r.stdout
    assert os.path.exists(victim)  # the real tree untouched
