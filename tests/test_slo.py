"""Fleet observability (PR 17): the streaming metrics registry, the
SLO engine's multi-window burn-rate alerting, the ``report --slo``
dashboard, and THE acceptance drill — a 10x load spike against a
2-replica Router breaches a declared objective, fires a dated ``slo``
breach event with a flight-record dump, renders in ``report --slo``,
and recovers to a green machine-readable ``Router.health()`` once the
spike rolls out of the compliance window.

Unit layers run on an injected fake clock (no sleeps); the drill runs
through the REAL export → cold-load → subprocess-replica path."""

import glob
import json
import os
import time

import numpy as np
import pytest

from roc_tpu.obs.metrics_registry import MetricsRegistry
from roc_tpu.obs.slo import BURN_RULES, Slo, SloEngine, parse_slo

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fake_reg(name="t", t0=1000.0):
    clk = [t0]
    return clk, MetricsRegistry(name, now=lambda: clk[0])


# ------------------------------------------------- registry primitives

def test_counter_windowed_sums():
    clk, reg = _fake_reg()
    c = reg.counter("requests")
    c.inc(5)
    clk[0] += 30.0
    c.inc(2)
    assert c.total == 7                  # lifetime: an attribute
    assert c.sum_over(10.0) == 2         # trailing window
    assert c.sum_over(60.0) == 7
    assert c.rate(10.0) == pytest.approx(0.2)
    clk[0] += 300.0                      # everything expires
    assert c.sum_over(60.0) == 0
    assert c.total == 7                  # lifetime survives the ring
    snap = c.snapshot((10.0,))
    assert snap["kind"] == "counter" and snap["total"] == 7
    assert snap["sum_10s"] == 0


def test_counter_get_or_create_idempotent():
    clk, reg = _fake_reg()
    a = reg.counter("x")
    a.inc(3)
    assert reg.counter("x") is a
    assert reg.counter("x").total == 3


def test_histogram_quantiles_windowed():
    clk, reg = _fake_reg()
    h = reg.histogram("request_ms")
    for v in [1.0] * 90 + [100.0] * 10:
        h.record(v)
    # log-bucket midpoints: within one bucket (~16% relative) of exact
    assert h.quantile(0.50, 60.0) == pytest.approx(1.0, rel=0.2)
    assert h.quantile(0.99, 60.0) == pytest.approx(100.0, rel=0.2)
    assert h.frac_above(10.0, 60.0) == pytest.approx(0.10)
    assert h.count_over(60.0) == 100
    clk[0] += 30.0
    h.record(5.0)
    # the 10 s window only sees the new sample
    assert h.count_over(10.0) == 1
    assert h.quantile(0.5, 10.0) == pytest.approx(5.0, rel=0.2)
    # lifetime view keeps everything
    assert h.count_over(None) == 101
    assert h.quantile(0.99, None) == pytest.approx(100.0, rel=0.2)
    snap = h.snapshot((10.0,))
    assert snap["kind"] == "histogram"
    assert snap["n_10s"] == 1 and snap["total"] == 101
    # empty window: honest None, not 0
    clk[0] += 300.0
    assert h.quantile(0.99, 10.0) is None
    assert h.frac_above(10.0, 10.0) == 0.0


def test_gauge_value_and_ewma():
    _, reg = _fake_reg()
    g = reg.gauge("step_ewma_ms", ewma_alpha=0.5)
    assert g.value is None and g.ewma is None
    g.set(100.0)
    g.set(200.0)
    assert g.value == 200.0
    assert g.ewma == pytest.approx(150.0)
    plain = reg.gauge("ratio")
    plain.set(1.5)
    assert plain.ewma == 1.5             # no alpha: ewma == value


def test_registry_snapshot_and_dump(tmp_path):
    clk, reg = _fake_reg("router")
    reg.counter("ok").inc(9)
    reg.histogram("request_ms").record(2.0)
    reg.gauge("ratio").set(1.1)
    doc = reg.snapshot(windows=(10.0, 60.0))
    assert doc["registry"] == "router"
    assert doc["windows_s"] == [10.0, 60.0]
    assert doc["metrics"]["ok"]["sum_10s"] == 9
    p = str(tmp_path / "snap.json")
    reg.dump(p, windows=(10.0,), extra={"component": "router"})
    loaded = json.load(open(p))
    assert loaded["component"] == "router"
    assert loaded["metrics"]["ok"]["total"] == 9
    assert "t" in loaded                 # wall stamp for the watcher


# ------------------------------------------------------- SLO grammar

def test_parse_slo_availability_roundtrip():
    s = parse_slo("availability(ok/requests) >= 0.999 over 60s")
    assert s.kind == "availability"
    assert (s.ok, s.total) == ("ok", "requests")
    assert s.target == 0.999 and s.window_s == 60.0
    assert s.budget == pytest.approx(0.001)
    assert parse_slo(s.spec()).spec() == s.spec()


def test_parse_slo_latency_named():
    s = parse_slo("lat99: p99(request_ms) <= 50ms over 30s")
    assert s.name == "lat99" and s.kind == "latency"
    assert s.hist == "request_ms"
    assert s.q == 0.99 and s.limit_ms == 50.0
    assert s.budget == pytest.approx(0.01)
    assert parse_slo(s.spec()).spec() == s.spec()


def test_parse_slo_rejects_garbage_and_zero_budget():
    with pytest.raises(ValueError):
        parse_slo("p99 of latency under 50")
    with pytest.raises(ValueError):
        parse_slo("availability(ok/requests) >= 1.0 over 60s")
    with pytest.raises(ValueError):
        Slo("x", "throughput", 60.0, 0.9)


# ---------------------------------------------- burn-rate engine (fake clock)

def _engine(clk, reg, specs, **kw):
    kw.setdefault("flight_record", False)
    kw.setdefault("warmup_s", 0.0)
    return SloEngine(reg, specs, component="test",
                     now=lambda: clk[0], **kw)


def test_burn_rate_breach_and_recovery_edges():
    """The full transition arc on a fake clock: healthy traffic is
    green; a bad burst fires the burn-rate rules exactly once
    (edge-triggered); recovery waits for BOTH rules quiet AND window
    compliance, then emits exactly one recovered transition."""
    clk, reg = _fake_reg()
    eng = _engine(clk, reg,
                  ["availability(ok/requests) >= 0.9 over 60s"])
    req, ok = reg.counter("requests"), reg.counter("ok")
    req.inc(100), ok.inc(100)
    v = eng.evaluate()
    assert v["ok"] is True
    assert v["states"]["availability_60s"] == "ok"
    # 10x spike, 90% of it failing: bad_frac 0.9 / budget 0.1 = 9x
    # burn >= the slow rule's 6x on both its windows
    clk[0] += 1.0
    req.inc(1000), ok.inc(100)
    v = eng.evaluate()
    ob = v["objectives"][0]
    assert ob["firing"] is True
    assert ob["burn"] >= 6.0
    assert v["states"]["availability_60s"] == "breach"
    assert v["ok"] is False
    # still firing: NO second transition (edge-triggered)
    v2 = eng.evaluate()
    assert v2["states"]["availability_60s"] == "breach"
    # burst expires from every window -> quiet AND compliant
    clk[0] += 130.0
    req.inc(50), ok.inc(50)
    v3 = eng.evaluate()
    assert v3["states"]["availability_60s"] == "ok"
    assert v3["ok"] is True


def test_latency_objective_burns_on_slow_tail():
    clk, reg = _fake_reg()
    eng = _engine(clk, reg, ["p95(request_ms) <= 10ms over 60s"])
    h = reg.histogram("request_ms")
    for _ in range(100):
        h.record(2.0)
    assert eng.evaluate()["ok"] is True
    # half the traffic above the limit: bad 0.5 / budget 0.05 = 10x
    for _ in range(100):
        h.record(50.0)
    v = eng.evaluate()
    assert v["states"]["p95_request_ms"] == "breach"
    assert v["objectives"][0]["value"] == pytest.approx(50.0, rel=0.2)


def test_warmup_suppresses_startup_false_positive():
    """Availability counts a request at submit and its ok only at
    completion — the first evaluations after traffic starts see
    bad_frac ~ 1 over a tiny sample.  The warmup guard keeps rules
    from firing until traffic has flowed for warmup_s."""
    clk, reg = _fake_reg()
    eng = _engine(clk, reg,
                  ["availability(ok/requests) >= 0.9 over 60s"],
                  warmup_s=2.0)
    req, ok = reg.counter("requests"), reg.counter("ok")
    req.inc(20)                          # submitted, none complete yet
    v = eng.evaluate()
    assert v["states"]["availability_60s"] == "ok"
    assert v["objectives"][0].get("warmup") is True
    # completions land; past warmup the same traffic is green
    ok.inc(20)
    clk[0] += 3.0
    assert eng.evaluate()["states"]["availability_60s"] == "ok"
    # and a GENUINE post-warmup burst still fires
    req.inc(1000), ok.inc(100)
    assert eng.evaluate()["states"]["availability_60s"] == "breach"


def test_breach_emits_dated_event_and_flight_record(tmp_path,
                                                    monkeypatch):
    """The alert surface: entering breach emits one dated ``slo``
    event on the bus and dumps the PR-9 flight record; recovery emits
    the matching ``recovered`` event."""
    from roc_tpu.obs import events
    ev_path = str(tmp_path / "ev.jsonl")
    monkeypatch.setenv("ROC_TPU_FLIGHT_DIR", str(tmp_path))
    events.configure(jsonl_path=ev_path)
    try:
        clk, reg = _fake_reg()
        eng = _engine(clk, reg,
                      ["availability(ok/requests) >= 0.9 over 60s"],
                      flight_record=True)
        req, ok = reg.counter("requests"), reg.counter("ok")
        req.inc(1000), ok.inc(50)
        eng.evaluate()
        clk[0] += 130.0
        req.inc(50), ok.inc(50)
        eng.evaluate()
    finally:
        events.configure(jsonl_path=None)
    recs = [json.loads(ln) for ln in open(ev_path) if ln.strip()]
    slo = [r for r in recs if r.get("cat") == "slo"]
    assert [r["kind"] for r in slo] == ["breach", "recovered"]
    br = slo[0]
    assert br["slo"] == "availability_60s"
    assert br["component"] == "test"
    assert br["burn"] >= 6.0
    assert isinstance(br["t"], float)    # dated: wall-clock stamped
    dumps = glob.glob(str(tmp_path / "flightrecord_*slo-breach*"))
    assert len(dumps) == 1


def test_tick_rate_limits_and_caches():
    clk, reg = _fake_reg()
    eng = _engine(clk, reg,
                  ["availability(ok/requests) >= 0.9 over 60s"],
                  eval_interval_s=0.25)
    reg.counter("requests").inc(10), reg.counter("ok").inc(10)
    v1 = eng.tick()
    assert v1 is not None and v1["ok"] is True
    assert eng.tick() is v1              # within interval: cached
    clk[0] += 0.3
    assert eng.tick() is not v1          # fresh evaluation


# -------------------------------------------------- report --slo golden

def test_report_slo_dashboard_golden(tmp_path, capsys):
    """``python -m roc_tpu.report --slo snap.json`` renders the
    snapshot as the watch-able dashboard: health verdict, objectives
    table, counters/gauges/histograms with their windowed views."""
    from roc_tpu import report
    clk, reg = _fake_reg("router")
    reg.counter("requests").inc(120)
    reg.counter("ok").inc(119)
    h = reg.histogram("request_ms")
    for v in [2.0] * 99 + [40.0]:
        h.record(v)
    reg.gauge("inflight").set(3)
    eng = _engine(clk, reg,
                  ["availability(ok/requests) >= 0.99 over 60s",
                   "lat99: p99(request_ms) <= 50ms over 60s"])
    snap = str(tmp_path / "snap.json")
    reg.dump(snap, windows=(10.0, 60.0),
             extra={"component": "router",
                    "health": {**eng.evaluate(),
                               "replicas_alive": 2, "replicas": 2}})
    rc = report.main(["--slo", snap])
    out = capsys.readouterr().out
    assert rc == 0
    assert "slo dashboard" in out and "component=router" in out
    assert "health: OK" in out and "(2/2 replicas alive)" in out
    assert "availability_60s" in out and "lat99" in out
    assert "requests" in out and "request_ms" in out
    assert "inflight" in out
    # a breach snapshot renders BREACH, not a stack trace
    reg.counter("requests").inc(500)
    reg.dump(snap, windows=(10.0,),
             extra={"component": "router",
                    "health": {**eng.evaluate(),
                               "replicas_alive": 1, "replicas": 2}})
    rc = report.main(["--slo", snap])
    out = capsys.readouterr().out
    assert rc == 0 and "health: BREACH" in out


def test_report_slo_requires_input(capsys):
    from roc_tpu import report
    with pytest.raises(SystemExit):
        report.main(["--slo"])           # bare --slo with no events


# ------------------------------------------ the e2e spike drill (subprocess)

@pytest.fixture(scope="module", autouse=True)
def _shed_native_jit_state():
    """Same PR-7/8 mitigation as the other serve modules: shed the
    native JIT state accumulated by the export below."""
    yield
    import jax
    jax.clear_caches()


@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    """One exported precomputed artifact + warm persistent cache (the
    replicas cold-load with zero new compiles)."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.sgc import build_sgc
    from roc_tpu.serve.export import build_predictor, export_predictor
    from roc_tpu.train.trainer import TrainConfig
    d = tmp_path_factory.mktemp("slo_art")
    cache = str(d / "cache")
    os.makedirs(cache)
    os.environ["ROC_TPU_CACHE_DIR"] = cache
    os.environ["ROC_TPU_CACHE_MIN_SECS"] = "0"
    ds = synthetic_dataset(num_nodes=300, avg_degree=6, in_dim=24,
                           num_classes=5, seed=0)
    pred = build_predictor(build_sgc([24, 5], k=2, dropout_rate=0.5),
                           ds, TrainConfig(verbose=False,
                                           symmetric=True),
                           backend="precomputed")
    art = str(d / "artifact")
    export_predictor(pred, art,
                     dataset_meta={"V": ds.graph.num_nodes,
                                   "E": int(ds.graph.num_edges)})
    yield art, ds
    os.environ.pop("ROC_TPU_CACHE_DIR", None)


def test_slo_spike_breach_recovery_e2e(artifact, tmp_path,
                                       monkeypatch):
    """THE PR-17 acceptance drill, through the real export →
    cold-load → subprocess-replica path: a 10x spike of unmeetable-
    deadline requests against a 2-replica Router burns the declared
    availability budget — the engine fires a dated ``slo`` breach
    event with a flight-record dump and ``health()`` goes red; once
    the spike rolls out of the compliance window under quiet
    successful traffic, a ``recovered`` event fires and ``health()``
    returns green with windowed availability 1.0.  The snapshot feed
    + event stream render in ``report --slo``."""
    from roc_tpu.obs import events
    from roc_tpu.serve.errors import ServeTimeout
    from roc_tpu.serve.router import Router
    art, ds = artifact
    ev_path = str(tmp_path / "ev.jsonl")
    snap_path = str(tmp_path / "snap.json")
    monkeypatch.setenv("ROC_TPU_FLIGHT_DIR", str(tmp_path))
    events.configure(jsonl_path=ev_path)
    env = os.environ.copy()
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("ROC_TPU_FAULT", None)
    ids = np.arange(4, dtype=np.int32)
    slo_name = "availability_8s"
    try:
        with Router(art, n_replicas=2, cpu=True, env=env,
                    default_deadline_ms=30_000.0, stats_window_s=8.0,
                    slos=("availability(ok/requests) >= 0.95 "
                          "over 8s",),
                    snapshot_path=snap_path) as router:
            # quiet phase: warm both replicas, pass the engine warmup
            t_end = time.monotonic() + 3.0
            while time.monotonic() < t_end:
                router.submit(ids).result(timeout=60)
                time.sleep(0.05)
            assert router.health()["ok"] is True
            # 10x spike with unmeetable deadlines: every request
            # times out, bad_frac ~ 1 against a 0.05 budget
            spike = [router.submit(ids, deadline_ms=0.2)
                     for _ in range(150)]
            timeouts = 0
            for f in spike:
                try:
                    f.result(timeout=60)
                except ServeTimeout:
                    timeouts += 1
            assert timeouts > 100
            deadline = time.monotonic() + 10.0
            breached = False
            while time.monotonic() < deadline:
                h = router.health()
                if h["states"].get(slo_name) == "breach":
                    breached = True
                    break
                time.sleep(0.2)
            assert breached, h
            assert h["ok"] is False
            # recovery: quiet successful traffic until the spike is
            # outside the 8 s compliance window
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                router.submit(ids).result(timeout=60)
                h = router.health()
                if h["ok"] and h["states"].get(slo_name) == "ok":
                    break
                time.sleep(0.2)
            assert h["ok"] is True, h
            assert h["states"][slo_name] == "ok"
            stats = router.stats()
            assert stats["availability"] == 1.0
            assert stats["window_s"] == 8.0
    finally:
        events.configure(jsonl_path=None)
    # the alert trail: dated breach + recovered slo events
    recs = [json.loads(ln) for ln in open(ev_path) if ln.strip()]
    slo_evs = [r for r in recs if r.get("cat") == "slo"]
    kinds = [r["kind"] for r in slo_evs]
    assert "breach" in kinds and "recovered" in kinds
    assert kinds.index("breach") < kinds.index("recovered")
    br = next(r for r in slo_evs if r["kind"] == "breach")
    assert br["slo"] == slo_name and br["component"] == "router"
    assert isinstance(br["t"], float)
    # flight record dumped at the breach edge
    assert glob.glob(str(tmp_path / "flightrecord_*slo-breach*"))
    # the live snapshot feed exists and report --slo renders both the
    # dashboard and the dated transition table
    assert os.path.exists(snap_path)
    import io
    from roc_tpu import report
    buf_rc = report.main(["--slo", snap_path, ev_path])
    assert buf_rc == 0
