"""roc-lint level eight (analysis/protocol_lint + modelcheck +
protocol_specs): every protocol rule fires on a synthetic violation
tree, each model's seeded bug makes the bounded checker bite with a
counterexample schedule, the REAL tree audits clean with an empty
findings baseline, the static-vs-declared spec tables agree, the CLI
gate (and its `--select protocol` alias) bites, and the replica's
unknown-wire-kind rejection (the true positive this level fixed on
landing) holds as a drill-style regression."""

import io
import json
import os
import subprocess
import sys
import time

from roc_tpu.analysis import protocol_specs as specs
from roc_tpu.analysis.concurrency_lint import (TreeModel,
                                               run_concurrency_lint)
from roc_tpu.analysis.modelcheck import (
    MODELS, SEEDS, STATE_BUDGET, ModelReport, check_all,
    model_invariants, run_model)
from roc_tpu.analysis.protocol_lint import (
    PROTOCOL_RULES, protocol_surface, run_protocol_lint)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ROUTER = "roc_tpu/serve/router.py"
_REPLICA = "roc_tpu/serve/replica.py"


def _plant(root, relpath, text):
    p = root / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(text)


def _keys(findings):
    return sorted(f.key for f in findings)


# -------------------------------------------- wire-vocabulary fixtures

def test_wire_vocabulary_sent_unhandled_fires(tmp_path):
    """A kind put on the wire with no receiver branch fires once per
    kind (not per send site); a fully-handled kind stays quiet."""
    _plant(tmp_path, _ROUTER,
           "def run(wire, sub):\n"
           "    wire.send({'kind': 'req', 'id': 1, 'ids': [],\n"
           "               'deadline_ms': None, 'rid': None})\n"
           "    wire.send({'kind': 'bogus', 'x': 1})\n"
           "    wire.send({'kind': 'bogus', 'x': 2})\n")
    _plant(tmp_path, _REPLICA,
           "def read_loop(msg):\n"
           "    kind = msg.get('kind')\n"
           "    if kind == 'close':\n"
           "        return\n"
           "    if kind != 'req':\n"
           "        raise ValueError(kind)\n"
           "    go(msg)\n")
    got = run_protocol_lint(str(tmp_path), select=["wire-vocabulary"])
    assert _keys(got) == ["sent-unhandled|router->replica|bogus"], \
        [(f.key, f.msg) for f in got]
    assert "no branch for it" in got[0].msg


def test_wire_vocabulary_handled_unsent_and_spec_sanction(tmp_path):
    """A receiver branch for a kind the sender never puts on the wire
    is dead vocabulary — except when the spec sanctions it with
    ``sent: False`` (close: stdin EOF is the close signal)."""
    _plant(tmp_path, _ROUTER,
           "def run(wire):\n"
           "    wire.send({'kind': 'req', 'id': 1, 'ids': [],\n"
           "               'deadline_ms': None, 'rid': None})\n")
    _plant(tmp_path, _REPLICA,
           "def read_loop(msg):\n"
           "    kind = msg.get('kind')\n"
           "    if kind == 'close':\n"       # sanctioned: sent False
           "        return\n"
           "    if kind == 'zombie':\n"      # dead vocabulary
           "        return\n"
           "    if kind != 'req':\n"
           "        raise ValueError(kind)\n"
           "    go(msg)\n")
    got = run_protocol_lint(str(tmp_path), select=["wire-vocabulary"])
    assert _keys(got) == ["handled-unsent|router->replica|zombie"], \
        [(f.key, f.msg) for f in got]
    assert "dead vocabulary" in got[0].msg


def test_wire_vocabulary_missing_unknown_kind_rejection(tmp_path):
    """A kind-dispatching receiver with neither a != guard nor a
    final else is the replica:146 bug class — a typo'd kind silently
    falls through; adding the guard clears it."""
    _plant(tmp_path, _ROUTER,
           "def run(wire):\n"
           "    wire.send({'kind': 'req', 'id': 1, 'ids': [],\n"
           "               'deadline_ms': None, 'rid': None})\n")
    _plant(tmp_path, _REPLICA,
           "def read_loop(msg):\n"
           "    kind = msg.get('kind')\n"
           "    if kind == 'close':\n"
           "        return\n"
           "    if kind == 'req':\n"
           "        go(msg)\n")
    got = run_protocol_lint(str(tmp_path), select=["wire-vocabulary"])
    assert _keys(got) == \
        ["no-unknown-rejection|router->replica|read_loop"], \
        [(f.key, f.msg) for f in got]

    # the ==-chain-with-final-else shape is an accepted rejection too
    _plant(tmp_path, _REPLICA,
           "def read_loop(msg):\n"
           "    kind = msg.get('kind')\n"
           "    if kind == 'close':\n"
           "        return\n"
           "    elif kind == 'req':\n"
           "        go(msg)\n"
           "    else:\n"
           "        reject(kind)\n")
    assert not run_protocol_lint(str(tmp_path),
                                 select=["wire-vocabulary"])


# ----------------------------------------- wire-field-contract fixtures

def test_wire_field_contract_missing_and_undeclared(tmp_path):
    """A send site that omits a required field or carries an
    undeclared one fires; the exact declared shape stays quiet; a
    helper-built payload (the _error_payload idiom) resolves one
    level deep."""
    _plant(tmp_path, _ROUTER,
           "def _payload(i):\n"
           "    return {'kind': 'req', 'id': i, 'ids': [],\n"
           "            'deadline_ms': None, 'rid': None}\n"
           "def run(wire):\n"
           "    wire.send(_payload(1))\n"       # helper: exact shape
           "    wire.send({'kind': 'req', 'id': 2, 'ids': [],\n"
           "               'deadline_ms': None})\n"      # missing rid
           "    wire.send({'kind': 'req', 'id': 3, 'ids': [],\n"
           "               'deadline_ms': None, 'rid': None,\n"
           "               'hedge': True})\n")           # undeclared
    got = run_protocol_lint(str(tmp_path),
                            select=["wire-field-contract"])
    assert _keys(got) == [
        "missing|router->replica|req|rid",
        "undeclared|router->replica|req|hedge",
    ], [(f.key, f.msg) for f in got]
    assert all(f.rule == "wire-field-contract" for f in got)


# ------------------------------------------ protocol-spec-drift fixtures

def test_spec_drift_flags_stale_rows_and_missing_sites(tmp_path):
    """A skeleton tree that no longer sends/handles the declared
    vocabulary and lost its declared transition sites drifts in
    every direction the rule covers."""
    _plant(tmp_path, _ROUTER, "def run(wire):\n    pass\n")
    _plant(tmp_path, _REPLICA, "def read_loop(msg):\n    pass\n")
    got = run_protocol_lint(str(tmp_path),
                            select=["protocol-spec-drift"])
    keys = set(_keys(got))
    assert "unsent|router->replica|req" in keys
    assert "unhandled|router->replica|close" in keys
    assert f"missing-site|{_ROUTER}|Router.submit" in keys
    assert f"missing-site|{_REPLICA}|serve_loop" in keys
    # 'close' is declared sent: False — its absence from the send
    # sites is NOT drift
    assert "unsent|router->replica|close" not in keys


def test_spec_drift_flags_undeclared_and_despite_spec_kinds(tmp_path):
    """An observed kind the spec lacks (both directions) and a send
    of a declared never-sent kind are drift — the spec must be
    edited FIRST."""
    _plant(tmp_path, _ROUTER,
           "def run(wire):\n"
           "    wire.send({'kind': 'promote', 'id': 1})\n"
           "    wire.send({'kind': 'close'})\n")
    _plant(tmp_path, _REPLICA,
           "def read_loop(msg):\n"
           "    kind = msg.get('kind')\n"
           "    if kind == 'promote':\n"
           "        go(msg)\n")
    got = run_protocol_lint(str(tmp_path),
                            select=["protocol-spec-drift"])
    keys = set(_keys(got))
    assert "undeclared-kind|router->replica|promote" in keys
    assert "sent-despite-spec|router->replica|close" in keys
    despite = [f for f in got
               if f.key == "sent-despite-spec|router->replica|close"]
    assert "stdin EOF" in despite[0].msg    # the spec note travels


def test_spec_drift_catches_invariant_table_drift(tmp_path):
    """The checker's implemented invariant set is cross-checked
    against the declared MODEL_INVARIANTS — drift in either
    direction (doctored reports here) is a finding."""
    doctored = [ModelReport(name="router-lifecycle",
                            invariants=("terminal-exactly-once",))]
    got = run_protocol_lint(str(tmp_path),
                            select=["protocol-spec-drift"],
                            model_reports=doctored)
    keys = set(_keys(got))
    assert "invariant-drift|router-lifecycle" in keys
    # the other two models are declared but absent from the reports
    assert "invariant-drift|ckpt-commit" in keys
    assert "invariant-drift|table-swap" in keys


def test_static_invariants_match_declared_spec():
    """The spec-equality pin: modelcheck's implemented invariant
    names equal protocol_specs.MODEL_INVARIANTS exactly, per model —
    the drift rule's clean verdict on the real tree is this equality,
    not a vacuous pass."""
    assert model_invariants() == {
        m: tuple(v) for m, v in specs.MODEL_INVARIANTS.items()}
    assert set(MODELS) == set(specs.MODEL_INVARIANTS)


# ------------------------------------------------- the model checker

def test_models_explore_exhaustively_and_fast():
    """All three shipped models explore to completion well inside the
    state budget, find zero violations, and the whole pass stays in
    the millisecond preflight class (asserted wall-time bound)."""
    t0 = time.monotonic()
    reports = check_all()
    wall = time.monotonic() - t0
    assert wall < 2.0, f"model check took {wall:.2f}s"
    assert [r.name for r in reports] == list(MODELS)
    for r in reports:
        assert r.complete, r.name
        assert r.violations == [], (r.name, r.violations)
        assert 0 < r.states < STATE_BUDGET, (r.name, r.states)
        assert r.transitions >= r.states - 1


def test_seeded_double_requeue_bites():
    """Dropping the per-corpse requeue guard (the seeded router bug)
    violates failover-requeue-at-most-once with a concrete
    crash/mark-dead schedule."""
    rep = run_model("router-lifecycle",
                    seed=SEEDS["router-lifecycle"])
    bad = {v["invariant"] for v in rep.violations}
    assert "failover-requeue-at-most-once" in bad, rep.violations
    v = next(x for x in rep.violations
             if x["invariant"] == "failover-requeue-at-most-once")
    assert v["trace"], "counterexample schedule must be non-empty"
    assert any("markdead" in step for step in v["trace"])


def test_seeded_manifest_first_bites():
    """Publishing the manifest before the shard rename (the seeded
    commit bug) violates publish-last AND the restore-side torn-state
    invariant — the two views of the same window."""
    rep = run_model("ckpt-commit", seed=SEEDS["ckpt-commit"])
    bad = {v["invariant"] for v in rep.violations}
    assert "manifest-published-last" in bad, rep.violations
    assert "restore-never-torn" in bad, rep.violations


def test_seeded_swap_mid_query_bites():
    """Reading the live published version per row instead of the
    microbatch capture (the seeded swap bug) violates
    single-version-batch."""
    rep = run_model("table-swap", seed=SEEDS["table-swap"])
    bad = {v["invariant"] for v in rep.violations}
    assert bad == {"single-version-batch"}, rep.violations


def test_seeded_live_qmode_bites():
    """Selecting the dequant program by the LIVE published version's
    quant spec instead of the captured one (the PR-19 seeded bug —
    the mid-rollout fp32→int8 window) violates quant-spec-pinned and
    ONLY that: the captured rows themselves are still consistent, so
    single-version-batch must stay green."""
    rep = run_model("table-swap", seed="live-qmode")
    bad = {v["invariant"] for v in rep.violations}
    assert bad == {"quant-spec-pinned"}, rep.violations
    # the original swap bug is unchanged by the quant extension
    rep2 = run_model("table-swap", seed=SEEDS["table-swap"])
    assert {v["invariant"] for v in rep2.violations} == \
        {"single-version-batch"}, rep2.violations


def test_seeded_shard_gather_bites():
    """Merging gathered foreign rows from whatever version the owner
    publishes at answer time instead of refusing the mismatched pin
    (the PR-20 seeded bug — the owner republished between capture and
    gather) violates gather-version-pinned and ONLY that: the locally
    owned rows still come from the captured version, so
    single-version-batch and quant-spec-pinned stay green."""
    rep = run_model("table-swap", seed="shard-gather")
    bad = {v["invariant"] for v in rep.violations}
    assert bad == {"gather-version-pinned"}, rep.violations
    # the sibling seeds are unchanged by the gather extension
    rep2 = run_model("table-swap", seed="live-qmode")
    assert {v["invariant"] for v in rep2.violations} == \
        {"quant-spec-pinned"}, rep2.violations


def test_modelcheck_findings_carry_schedule_and_budget(tmp_path):
    """A violation report becomes a modelcheck-invariant finding
    carrying the counterexample schedule; an exhausted budget is
    itself a finding (an unexplorable model proves nothing)."""
    seeded = run_model("table-swap", seed=SEEDS["table-swap"])
    got = run_protocol_lint(str(tmp_path),
                            select=["modelcheck-invariant"],
                            model_reports=[seeded])
    assert _keys(got) == ["table-swap|single-version-batch"]
    assert "[schedule: " in got[0].msg
    assert got[0].detail["trace"]
    assert got[0].unit == "model:table-swap"

    tiny = run_model("router-lifecycle", budget=10)
    assert not tiny.complete
    got = run_protocol_lint(str(tmp_path),
                            select=["modelcheck-invariant"],
                            model_reports=[tiny])
    assert _keys(got) == ["router-lifecycle|budget"]
    assert "state budget" in got[0].msg


def test_unknown_model_and_seed_raise():
    import pytest
    with pytest.raises(ValueError):
        run_model("nope")
    with pytest.raises(ValueError):
        run_model("table-swap", seed="double-requeue")


# ------------------------------- ckpt-commit-order (migrated, PR 15→18)

def test_commit_order_fires_on_manifest_before_shard_rename(tmp_path):
    """Checkpoint-v3 two-phase-commit ORDER, now owned by the
    protocol level: a writer publishing the manifest BEFORE a shard
    rename re-creates the torn-read window — the lint bites under its
    own rule name; the correct order and a pragma'd site pass."""
    _plant(tmp_path, "roc_tpu/ck.py",
           "import os\n"
           "from roc_tpu.utils.checkpoint import commit_manifest\n"
           "def bad_writer(d, snap, shards, tmp, shard):\n"
           "    commit_manifest(d, snap, shards)\n"           # line 4
           "    os.replace(tmp, shard)\n"
           "def good_writer(d, snap, shards, tmp, shard):\n"
           "    os.replace(tmp, shard)\n"
           "    commit_manifest(d, snap, shards)\n"
           "def waived_writer(d, snap, shards, tmp, shard):\n"
           "    commit_manifest(d, snap, shards)  "
           "# re-commit of a landed shard: roc-lint: "
           "ok=ckpt-commit-order\n"
           "    os.replace(tmp, shard)\n")
    got = run_protocol_lint(str(tmp_path),
                            select=["ckpt-commit-order"])
    assert [(f.rule, f.line) for f in got] == \
        [("ckpt-commit-order", 4)], [(f.line, f.msg) for f in got]
    assert "BEFORE a shard rename" in got[0].msg
    assert got[0].key == "commit-order|bad_writer"
    # the migration left NO duplicate behind: the concurrency level
    # no longer reports commit order (one source of truth)
    conc = run_concurrency_lint(str(tmp_path),
                                select=["artifact-lock-ownership"])
    assert conc == [], [(f.rule, f.msg) for f in conc]


# ------------------------------------------------- registration + tree

def test_rules_registered_and_not_trace():
    from roc_tpu.analysis.driver import all_rule_names, is_trace_rule
    from roc_tpu.obs.events import CATEGORIES
    names = all_rule_names()
    for r in PROTOCOL_RULES:
        assert r in names
        # pure AST + pure-Python BFS: a `--select protocol` preflight
        # must never force the jax trace rig
        assert not is_trace_rule(r)
    assert "protocol" in CATEGORIES


def test_tree_is_clean_and_baseline_empty():
    """The REAL tree audits clean (the replica's unknown-kind true
    positive was FIXED, not baselined): the findings baseline stays
    empty."""
    got = run_protocol_lint(_REPO)
    assert got == [], "\n".join(f.render() for f in got)
    data = json.load(open(
        os.path.join(_REPO, "scripts", "lint_baseline.json")))
    assert data["findings"] == []


def test_surface_documents_the_real_wire_protocol():
    """The extracted surface IS the protocol documentation: both
    channels, every kind status ok, every dispatcher rejecting
    unknown kinds, every declared transition site present, the
    helper-resolved res send sites included, and the checkpoint
    artifact inventory riding along (the PR-15 migration)."""
    reports = check_all()
    surface = protocol_surface(TreeModel(_REPO), reports)
    chans = {c["name"]: c for c in surface["channels"]}
    assert set(chans) == {"router->replica", "replica->router"}
    for c in chans.values():
        for kind, k in c["kinds"].items():
            assert k["status"] == "ok", (c["name"], kind, k)
        assert c["dispatchers"], c["name"]
        for d in c["dispatchers"]:
            assert d["rejects_unknown"], (c["name"], d)
    # close is declared never-sent with the stdin-EOF note
    close = chans["router->replica"]["kinds"]["close"]
    assert close["sent"] is False and close["sent_at"] == []
    assert "EOF" in close["note"]
    # res is sent from three sites: the ok callback, the error path
    # via the _error_payload helper, and the read_loop rejection
    res = chans["replica->router"]["kinds"]["res"]
    assert len(res["sent_at"]) == 3, res
    assert all(s["present"] for s in surface["sites"])
    arts = {a["module"]: a["artifacts"]
            for a in surface["artifacts"]}
    assert any(x["kind"] == "ckpt-manifest"
               for x in arts["roc_tpu/utils/checkpoint.py"])
    assert any(x["kind"] == "ckpt-shard"
               for x in arts["roc_tpu/resilience/async_save.py"])
    t = surface["totals"]
    assert t["channels"] == 2 and t["models"] == 3
    assert t["violations"] == 0 and t["states"] > 0
    assert t["sites"] == sum(len(v) for v in
                             list(specs.LIFECYCLE_SITES.values())
                             + list(specs.COMMIT_SITES.values()))
    assert surface["state_budget"] == STATE_BUDGET


def test_report_renders_protocol_tables():
    """roc_tpu.report renders the wire-vocabulary / model tables from
    the --json payload (``--protocol``) AND from the protocol_surface
    event an audited run leaves in its event stream."""
    from roc_tpu import report
    surface = protocol_surface(TreeModel(_REPO), check_all())
    out = io.StringIO()
    report.summarize([], protocol=surface, out=out)
    text = out.getvalue()
    assert "wire vocabulary: router->replica" in text
    assert "(by design)" in text            # close: sent False
    assert "unknown-kind rejection" in text
    assert "NO REJECTION" not in text
    assert "router-lifecycle" in text and "BUDGET EXHAUSTED" not in text
    assert "protocol transition sites" in text
    # event-stream path: same tables, no payload file needed
    ev = {"cat": "protocol", "kind": "protocol_surface",
          "channels": surface["channels"],
          "models": surface["models"], "totals": surface["totals"]}
    out2 = io.StringIO()
    report.summarize([ev], out=out2)
    text2 = out2.getvalue()
    assert "wire vocabulary: router->replica" in text2
    assert "router-lifecycle" in text2


# ------------------------------- the replica fix (drill-style regression)

def test_replica_rejects_unknown_wire_kind(monkeypatch):
    """The true positive this level fixed: an unknown wire kind used
    to fall through read_loop's close-check and dispatch AS A REQUEST.
    Now it comes back as a typed non-retryable error res (when it
    carries an id) and dispatches nothing — while a well-formed req on
    the same stdin still serves."""
    from roc_tpu.serve import replica as rep

    class _Fut:
        def add_done_callback(self, cb):
            pass

    class FakeServer:
        def __init__(self):
            self.submitted = []

        def submit(self, ids, deadline_ms=None, rid=None):
            self.submitted.append(list(ids))
            return _Fut()

        def drain(self, timeout=None):
            return True

    sent = []

    class FakeWire:
        def send(self, obj):
            sent.append(obj)

    stdin = io.StringIO(
        json.dumps({"kind": "promote", "id": 7}) + "\n"
        + json.dumps({"kind": "request", "ids": [9]}) + "\n"  # no id
        + json.dumps({"kind": "req", "id": 8, "ids": [1, 2]}) + "\n"
        + json.dumps({"kind": "close"}) + "\n")
    monkeypatch.setattr(rep.sys, "stdin", stdin)
    srv = FakeServer()
    clean = rep.serve_loop(srv, FakeWire(), replica=0,
                           drain_timeout_s=2.0)
    assert clean
    errs = [m for m in sent
            if m.get("kind") == "res" and m.get("ok") is False]
    assert [e["id"] for e in errs] == [7], sent
    assert errs[0]["error"] == "ServeError"
    assert "unknown wire kind 'promote'" in errs[0]["msg"]
    assert errs[0]["retryable"] is False
    # neither unknown kind dispatched anything; the real req did
    assert srv.submitted == [[1, 2]]
    assert sent[-1]["kind"] == "drained"


# --------------------------------------------------------- CLI wiring

def _run_cli(args, cwd=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "roc_tpu.analysis"] + args,
        cwd=cwd or _REPO, capture_output=True, text=True, timeout=120,
        env=env)


def test_cli_select_protocol_alias_green_on_tree():
    """`--select protocol` (the test.sh / round6_chain preflight
    line) expands to all five rules, runs jax-free fast, exits 0 on
    the tree, and the --json payload carries the surface with all
    three models explored to completion."""
    r = _run_cli(["--select", "protocol", "--json"])
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["summary"]["new"] == 0
    surface = payload["protocol_surface"]
    assert surface["totals"]["models"] == 3
    assert surface["totals"]["violations"] == 0
    for m in surface["models"]:
        assert m["complete"], m
        assert m["states"] > 0


def test_cli_ratchet_bites_on_planted_violation(tmp_path):
    """A seeded manifest-before-rename writer in a scratch tree fails
    the CLI through the alias (the ratchet bites from zero)."""
    _plant(tmp_path, "roc_tpu/ck.py",
           "import os\n"
           "def commit_manifest(d, snap, shards):\n"
           "    pass\n"
           "def bad_writer(d, snap, shards, tmp, shard):\n"
           "    commit_manifest(d, snap, shards)\n"
           "    os.replace(tmp, shard)\n")
    r = _run_cli(["--root", str(tmp_path), "--select", "protocol"])
    assert r.returncode == 1
    assert "ckpt-commit-order" in r.stdout
    assert "ck.py" in r.stdout


def test_cli_never_absorbs_protocol_findings(tmp_path):
    """--update-baseline must not absorb a live protocol finding
    (shrink-only contract, same as every level)."""
    _plant(tmp_path, "roc_tpu/ck.py",
           "import os\n"
           "def commit_manifest(d, snap, shards):\n"
           "    pass\n"
           "def bad_writer(d, snap, shards, tmp, shard):\n"
           "    commit_manifest(d, snap, shards)\n"
           "    os.replace(tmp, shard)\n")
    bp = tmp_path / "scripts" / "lint_baseline.json"
    bp.parent.mkdir()
    bp.write_text(json.dumps({"version": 1, "findings": []}))
    r = _run_cli(["--root", str(tmp_path), "--select", "protocol",
                  "--update-baseline"])
    assert r.returncode == 1
    assert json.loads(bp.read_text())["findings"] == []
