"""Vertex reordering (core/reorder.py): permutation correctness and
the locality mechanism it exists for."""

import numpy as np
import pytest

from roc_tpu.core.graph import Dataset, Graph, synthetic_dataset
from roc_tpu.core.reorder import (apply_vertex_order, bfs_order,
                                  cross_section_pairs)


def test_bfs_order_is_a_permutation():
    ds = synthetic_dataset(200, 6, in_dim=8, num_classes=3, seed=0)
    perm = bfs_order(ds.graph)
    assert np.array_equal(np.sort(perm), np.arange(200))


def test_reorder_preserves_graph_structure():
    """Edge (s, d) exists in the original iff (rank[s], rank[d])
    exists after reordering — exact edge-set isomorphism."""
    ds = synthetic_dataset(150, 5, in_dim=4, num_classes=3, seed=1)
    g = ds.graph
    new_ds, perm = apply_vertex_order(ds, bfs_order(g), "bfs")
    rank = np.argsort(perm)
    V = g.num_nodes

    def edge_set(graph):
        dst = np.repeat(np.arange(graph.num_nodes),
                        np.diff(graph.row_ptr))
        return set(zip(graph.col_idx.tolist(), dst.tolist()))

    orig = {(int(rank[s]), int(rank[d])) for s, d in edge_set(g)}
    assert orig == edge_set(new_ds.graph)
    # node data moved with the vertices
    np.testing.assert_array_equal(new_ds.labels, ds.labels[perm])
    np.testing.assert_array_equal(new_ds.features, ds.features[perm])
    np.testing.assert_array_equal(new_ds.mask, ds.mask[perm])
    # CSR stays monotone per row (loader convention)
    rp, ci = new_ds.graph.row_ptr, new_ds.graph.col_idx
    for i in range(V):
        row = ci[rp[i]:rp[i + 1]]
        assert np.all(np.diff(row) >= 0)


def test_reorder_preserves_edge_multiplicity():
    """Duplicate edges (multigraph multiplicities — the planted
    generators emit them) survive relabeling exactly; the edge-set
    isomorphism test above collapses them, this one counts."""
    from roc_tpu.core.graph import Graph
    from roc_tpu.core.reorder import apply_graph_order
    row_ptr = np.array([0, 3, 5, 6], dtype=np.int64)
    col_idx = np.array([1, 1, 2, 0, 0, 0], dtype=np.int32)
    g = Graph(row_ptr=row_ptr, col_idx=col_idx)
    perm = np.array([2, 0, 1], dtype=np.int64)  # new_id -> old_id
    out = apply_graph_order(g, perm)
    # old row 2 -> new row 0: [0] -> rank[0] = 1
    # old row 0 -> new row 1: [1,1,2] -> [rank1, rank1, rank2] = [2,2,0] sorted [0,2,2]
    # old row 1 -> new row 2: [0,0] -> [1,1]
    np.testing.assert_array_equal(out.row_ptr, [0, 1, 4, 6])
    np.testing.assert_array_equal(out.col_idx, [1, 0, 2, 2, 1, 1])


def test_training_metrics_invariant_under_reorder():
    """Same seed, dropout off: train/val/test metrics agree between
    the original and reordered datasets (the objective is a sum over
    vertices — relabeling-invariant up to fp association)."""
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer
    ds = synthetic_dataset(256, 7, in_dim=12, num_classes=4, seed=2)
    new_ds, _ = apply_vertex_order(ds, bfs_order(ds.graph), "bfs")
    metrics = []
    for d in (ds, new_ds):
        model = build_gcn([12, 16, 4], dropout_rate=0.0)
        tr = Trainer(model, d, TrainConfig(
            aggr_impl="ell", verbose=False, eval_every=1 << 30))
        tr.train(epochs=15)
        metrics.append(tr.evaluate())
    a, b = metrics
    assert a["train_loss"] == pytest.approx(b["train_loss"], rel=2e-3)
    assert a["test_acc"] == pytest.approx(b["test_acc"], abs=0.02)


def _planted_community_dataset(C=8, per=64, seed=0):
    """C communities of `per` vertices, edges almost entirely
    intra-community, vertex ids SHUFFLED (worst case for locality)."""
    rng = np.random.RandomState(seed)
    V = C * per
    shuffled = rng.permutation(V)
    src, dst = [], []
    for c in range(C):
        members = shuffled[c * per:(c + 1) * per]
        for _ in range(per * 6):
            s, d = rng.choice(members, 2)
            src.append(s)
            dst.append(d)
    from roc_tpu.core.graph import from_edge_list
    g = from_edge_list(np.array(src), np.array(dst), V)
    return Dataset(graph=g,
                   features=rng.rand(V, 8).astype(np.float32),
                   labels=rng.randint(0, 3, V).astype(np.int32),
                   mask=np.ones(V, np.int32), num_classes=3,
                   name="planted")


def test_bfs_shrinks_sectioned_tables_on_community_graph():
    """End-to-end: the actual SectionedEll layout (padded sub-rows =
    device memory + gather work) shrinks after reordering the planted
    community graph."""
    from roc_tpu.core.ell import section_sub_counts
    ds = _planted_community_dataset()
    new_ds, _ = apply_vertex_order(ds, bfs_order(ds.graph), "bfs")
    sec = 64

    def sub_rows(g):
        return int(section_sub_counts(g.row_ptr, g.col_idx,
                                      g.num_nodes, g.num_nodes,
                                      section_rows=sec).sum())

    before = sub_rows(ds.graph)
    after = sub_rows(new_ds.graph)
    # each sub-row is 8 gather slots; fewer sub-rows = smaller tables
    # and fewer padded gathers
    assert after * 2 <= before, (before, after)


def test_bfs_reduces_cross_section_pairs_on_community_graph():
    """The mechanism: on a community graph with shuffled ids, BFS
    relabeling clusters each neighborhood into few sections —
    cross-section (row, section) pairs, the sectioned layout's padding
    driver, drop by at least 2x at a community-sized section."""
    ds = _planted_community_dataset()
    sec = 64  # one community per section when perfectly clustered
    before = cross_section_pairs(ds.graph, sec)
    new_ds, _ = apply_vertex_order(ds, bfs_order(ds.graph), "bfs")
    after = cross_section_pairs(new_ds.graph, sec)
    assert after * 2 <= before, (before, after)


def test_cross_section_pairs_empty_graph():
    """Zero-edge graph: 0 pairs, not a ValueError from an empty-array
    reduction (ADVICE r3)."""
    g = Graph(row_ptr=np.zeros(6, dtype=np.int64),
              col_idx=np.zeros(0, dtype=np.int32))
    assert cross_section_pairs(g, 4) == 0


def test_lpa_order_is_a_permutation():
    from roc_tpu.core.reorder import lpa_order
    ds = synthetic_dataset(200, 6, in_dim=8, num_classes=3, seed=0)
    perm = lpa_order(ds.graph)
    assert np.array_equal(np.sort(perm), np.arange(200))


def test_lpa_recovers_planted_communities_for_bdense():
    """The claim the bdense path rides on: LPA relabeling of a
    SHUFFLED planted-community graph recovers (nearly) the oracle
    ordering's dense_frac, where BFS recovers only a sliver."""
    from roc_tpu.core.graph import planted_community_csr
    from roc_tpu.core.reorder import apply_graph_order, lpa_order
    from roc_tpu.ops.blockdense import plan_blocks

    # V large enough that a shuffled tile holds ~E*128^2/V^2 ~ 9
    # edges (below min_fill) while an oracle community tile holds
    # hundreds — the separation the pass exists to recover
    V, E, CR = 32768, 600_000, 1024
    oracle = planted_community_csr(V, E, community_rows=CR, seed=0,
                                   shuffle=False)
    shuf = planted_community_csr(V, E, community_rows=CR, seed=0,
                                 shuffle=True)
    occ_oracle = plan_blocks(oracle.row_ptr, oracle.col_idx, V,
                             min_fill=64).occupancy()
    occ_shuf = plan_blocks(shuf.row_ptr, shuf.col_idx, V,
                           min_fill=64).occupancy()
    fixed = apply_graph_order(shuf, lpa_order(shuf))
    occ_lpa = plan_blocks(fixed.row_ptr, fixed.col_idx, V,
                          min_fill=64).occupancy()
    assert occ_oracle["dense_frac"] > 0.5          # structure exists
    assert occ_shuf["dense_frac"] < 0.1            # ids hide it
    # LPA gets >= 90% of the oracle's dense fraction back
    assert occ_lpa["dense_frac"] >= 0.9 * occ_oracle["dense_frac"], \
        (occ_lpa, occ_oracle)


def test_lpa_sweep_native_matches_numpy():
    from roc_tpu import native
    if not native.available():
        pytest.skip("librocio not built")
    from roc_tpu.core.reorder import _lpa_sweep_numpy, _undirected_csr
    ds = synthetic_dataset(300, 7, in_dim=4, num_classes=3, seed=5)
    nbr_ptr, nbr = _undirected_csr(ds.graph)
    labels = np.arange(300, dtype=np.int32)
    for _ in range(3):
        got, ch_n = native.lpa_iterate(nbr_ptr,
                                       nbr.astype(np.int32), labels)
        want, ch_p = _lpa_sweep_numpy(nbr_ptr, nbr, labels, 300)
        np.testing.assert_array_equal(got, want)
        assert ch_n == ch_p
        labels = got


def test_training_metrics_invariant_under_lpa_reorder():
    from roc_tpu.core.reorder import lpa_order
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer
    ds = synthetic_dataset(256, 7, in_dim=12, num_classes=4, seed=2)
    new_ds, _ = apply_vertex_order(ds, lpa_order(ds.graph), "lpa")
    metrics = []
    for d in (ds, new_ds):
        model = build_gcn([12, 16, 4], dropout_rate=0.0)
        tr = Trainer(model, d, TrainConfig(
            aggr_impl="ell", verbose=False, eval_every=1 << 30))
        tr.train(epochs=15)
        metrics.append(tr.evaluate())
    a, b = metrics
    assert a["train_loss"] == pytest.approx(b["train_loss"], rel=2e-3)
    assert a["test_acc"] == pytest.approx(b["test_acc"], abs=0.02)


def test_lpa_star_graph_converges():
    """Fully-synchronous LPA 2-cycles on a star (center<->leaves swap
    labels forever); the asynchronous sweep must converge to a single
    stable labeling independent of max_iters parity."""
    from roc_tpu.core.graph import from_edge_list
    from roc_tpu.core.reorder import lpa_labels
    V = 41
    src = np.arange(1, V)          # leaves -> center edges
    dst = np.zeros(V - 1, dtype=np.int64)
    g = from_edge_list(src, dst, V)
    a = lpa_labels(g, max_iters=16)
    b = lpa_labels(g, max_iters=17)
    np.testing.assert_array_equal(a, b)
    assert len(np.unique(a)) == 1  # one community: the whole star


def test_lpa_same_parity_star_converges():
    """The round-5 reviewer's adversarial case for any fixed-parity
    semi-sync schedule: a star whose center AND leaves all have even
    ids (odd ids isolated).  The async sweep must still converge and
    be sweep-count independent."""
    from roc_tpu.core.graph import from_edge_list
    from roc_tpu.core.reorder import lpa_labels
    V = 12
    src = np.arange(2, V, 2)       # even leaves -> even center 0
    dst = np.zeros(src.shape[0], dtype=np.int64)
    g = from_edge_list(src, dst, V)
    a = lpa_labels(g, max_iters=16)
    b = lpa_labels(g, max_iters=17)
    np.testing.assert_array_equal(a, b)
    # the star collapses to one community; isolated odds keep theirs
    star = np.arange(0, V, 2)
    assert len(np.unique(a[star])) == 1
