"""Cost-model-driven partitioning (core/costmodel.py) + online load
rebalancing (DistributedTrainer.maybe_rebalance): split-search
invariants on skewed graphs, the online ridge fit, repartition
round-trips, recompile avoidance, and training parity against the
never-repartition run."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from roc_tpu.core import costmodel as CM
from roc_tpu.core.graph import (MASK_NONE, MASK_TRAIN, Dataset,
                                synthetic_dataset, synthetic_graph,
                                zipf_csr)
from roc_tpu.core.partition import (edge_balanced_bounds,
                                    materialize_plan, partition_bounds,
                                    partition_graph, partition_plan,
                                    plan_from_bounds)
from roc_tpu.models.gcn import build_gcn
from roc_tpu.obs.events import get_bus
from roc_tpu.parallel.distributed import DistributedTrainer
from roc_tpu.train.trainer import (TrainConfig, resolve_partition)


def _graphs():
    return [
        ("zipf", zipf_csr(512, 8192, a=1.0, seed=1)),
        ("lognormal", synthetic_graph(300, 7, seed=2, power_law=True)),
        ("uniform", synthetic_graph(200, 5, seed=3, power_law=False)),
    ]


def _check_invariants(bounds, num_parts, num_nodes):
    """Bounds are total, contiguous, len == P; empty ranges only in
    the tail."""
    assert len(bounds) == num_parts
    covered = []
    seen_empty = False
    for l, r in bounds:
        if r < l:
            seen_empty = True
        else:
            assert not seen_empty, "empty range before a real one"
            covered.extend(range(l, r + 1))
    assert covered == list(range(num_nodes))


class _Recorder:
    """Event sink capturing records for assertions."""

    def __init__(self):
        self.records = []

    def write(self, record):
        self.records.append(dict(record))

    def close(self):
        pass

    def of(self, cat):
        return [r for r in self.records if r.get("cat") == cat]


@pytest.fixture
def events():
    rec = _Recorder()
    bus = get_bus()
    bus.add_sink(rec)
    yield rec
    bus.sinks.remove(rec)


# ------------------------------------------------- split search

def test_vectorized_fallback_matches_loop_reference(monkeypatch):
    """The np.searchsorted sweep must be bit-identical to the original
    O(V) degree loop (and, transitively, the native path —
    tests/test_native.py pins native == python)."""
    from roc_tpu import native

    def loop_reference(row_ptr, num_parts):
        row_ptr = np.asarray(row_ptr, dtype=np.int64)
        num_nodes = row_ptr.shape[0] - 1
        cap = (int(row_ptr[-1]) + num_parts - 1) // num_parts
        bounds, left, cnt = [], 0, 0
        deg = np.diff(row_ptr)
        for v in range(num_nodes):
            cnt += int(deg[v])
            if cnt > cap and len(bounds) < num_parts - 1:
                bounds.append((left, v))
                cnt = 0
                left = v + 1
        bounds.append((left, num_nodes - 1))
        while len(bounds) < num_parts:
            bounds.append((num_nodes, num_nodes - 1))
        return bounds

    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    for name, g in _graphs():
        for P in (1, 2, 3, 4, 7, 8, 64):
            got = edge_balanced_bounds(g.row_ptr, P)
            want = loop_reference(g.row_ptr, P)
            assert got == want, (name, P)


@pytest.mark.parametrize("num_parts", [2, 4, 8])
def test_cost_bounds_invariants(num_parts):
    for name, g in _graphs():
        bounds = CM.cost_balanced_bounds(g.row_ptr, num_parts,
                                         node_multiple=8,
                                         edge_multiple=32)
        _check_invariants(bounds, num_parts, g.num_nodes)


def test_cost_never_worse_than_greedy_under_model():
    w = CM.PartitionCostModel().search_weights()
    for name, g in _graphs():
        for P in (2, 4, 8):
            greedy = edge_balanced_bounds(g.row_ptr, P)
            cost = CM.cost_balanced_bounds(g.row_ptr, P,
                                           node_multiple=8,
                                           edge_multiple=32,
                                           weights=w)
            c_g = CM.bounds_max_cost(g.row_ptr, greedy, w[0], w[1],
                                     8, 32)
            c_c = CM.bounds_max_cost(g.row_ptr, cost, w[0], w[1],
                                     8, 32)
            assert c_c <= c_g, (name, P, c_c, c_g)


def test_cost_strictly_better_on_zipf():
    """On the Zipf hub graph the greedy sweep's first-fit closes load
    the minimax search provably beats — the acceptance substrate."""
    g = zipf_csr(2048, 65_536, a=1.0, seed=5)
    w = CM.PartitionCostModel().search_weights()
    greedy = edge_balanced_bounds(g.row_ptr, 8)
    cost = CM.cost_balanced_bounds(g.row_ptr, 8, node_multiple=8,
                                   edge_multiple=128, weights=w)
    c_g = CM.bounds_max_cost(g.row_ptr, greedy, w[0], w[1], 8, 128)
    c_c = CM.bounds_max_cost(g.row_ptr, cost, w[0], w[1], 8, 128)
    assert c_c < c_g, (c_c, c_g)


def test_partition_bounds_dispatch_and_validation():
    g = zipf_csr(256, 2048, seed=0)
    assert partition_bounds(g.row_ptr, 4, method="greedy") == \
        edge_balanced_bounds(g.row_ptr, 4)
    got = partition_bounds(g.row_ptr, 4, method="cost",
                           node_multiple=8, edge_multiple=32)
    _check_invariants(got, 4, g.num_nodes)
    with pytest.raises(ValueError):
        partition_bounds(g.row_ptr, 4, method="metis")
    assert resolve_partition(TrainConfig()) == "cost"
    assert resolve_partition(TrainConfig(partition="greedy")) == \
        "greedy"
    with pytest.raises(ValueError):
        resolve_partition(TrainConfig(partition="spectral"))


@pytest.mark.parametrize("method", ["greedy", "cost"])
def test_plan_padding_invariants(method):
    """plan_from_bounds output obeys the padded-shard contract for
    BOTH split methods (the invariants the aggregators rely on)."""
    for name, g in _graphs():
        pg = partition_graph(g, 4, node_multiple=8, edge_multiple=32,
                             method=method)
        assert pg.part_nodes % 8 == 0
        assert pg.part_edges % 32 == 0
        assert (pg.part_row_ptr[:, -1] == pg.part_edges).all()
        assert pg.node_multiple == 8 and pg.edge_multiple == 32
        for p in range(4):
            l, r = pg.bounds[p]
            if r < l:
                continue
            e = int(pg.real_edges[p])
            np.testing.assert_array_equal(
                pg.part_col_idx[p, :e],
                g.col_idx[g.row_ptr[l]:g.row_ptr[r + 1]])
            assert (pg.part_col_idx[p, e:] == pg.dummy_src).all()


# ------------------------------------------------- cost model

def test_cost_model_prior_and_online_fit():
    m = CM.PartitionCostModel()
    # zero observations: weights ARE the prior (cold start == the
    # quantized edge-balance objective)
    w0 = m.weights_raw()
    np.testing.assert_allclose(w0, CM._PRIOR_RAW, atol=1e-9)
    # synthetic truth: t = 3 ms per 1k padded edges — the ridge must
    # converge to the signal and the search weights must track it
    rng = np.random.RandomState(0)
    for _ in range(200):
        phi = np.zeros(len(CM.PHI))
        phi[CM.PHI.index("intercept")] = 1.0
        phi[CM.PHI.index("padded_nodes")] = rng.randint(8, 4096)
        phi[CM.PHI.index("padded_edges")] = rng.randint(128, 1 << 20)
        t = 3e-3 * phi[CM.PHI.index("padded_edges")]
        m.observe(phi, t)
    w = m.weights_raw()
    assert w[CM.PHI.index("padded_edges")] == pytest.approx(3e-3,
                                                            rel=0.05)
    wn, we = m.search_weights()
    assert we == pytest.approx(3e-3, rel=0.05)
    assert wn >= 0.0
    # predictions follow
    phi = np.zeros((1, len(CM.PHI)))
    phi[0, CM.PHI.index("padded_edges")] = 1e6
    assert m.predict(phi)[0] == pytest.approx(3e3, rel=0.1)


def test_search_weights_never_degenerate():
    """Anti-correlated observations can drive the fitted size weights
    negative; the search must fall back to the prior, not produce a
    constant cost."""
    m = CM.PartitionCostModel()
    phi = np.zeros(len(CM.PHI))
    phi[CM.PHI.index("padded_edges")] = 1e6
    phi[CM.PHI.index("padded_nodes")] = 1e4
    for _ in range(50):
        m.observe(phi, -100.0)
    wn, we = m.search_weights()
    assert wn + we > 0


def test_phi_attention_and_flat8_columns():
    """The attention/flat8 φ columns fill only for workloads that run
    that code: attn_edges mirrors the padded edge count, flat8_chunks
    is the 8-wide sub-row count; both are 0 otherwise (keeping their
    fitted weights anchored to the prior for other workloads)."""
    g = synthetic_graph(120, 6, seed=7, power_law=True)
    pg = partition_graph(g, 4, node_multiple=8, edge_multiple=32)
    base = CM.phi_matrix(pg)
    ia = CM.PHI.index("attn_edges")
    ic = CM.PHI.index("flat8_chunks")
    assert (base[:, ia] == 0).all() and (base[:, ic] == 0).all()
    phi = CM.phi_matrix(pg, attn_edges=True, flat8=True)
    np.testing.assert_array_equal(
        phi[:, ia], phi[:, CM.PHI.index("padded_edges")])
    real_e = np.asarray(pg.real_edges, dtype=np.int64)
    np.testing.assert_array_equal(phi[:, ic], -(-real_e // 8))
    # the other columns are untouched by the flags
    np.testing.assert_array_equal(np.delete(base, (ia, ic), axis=1),
                                  np.delete(phi, (ia, ic), axis=1))


def test_attention_features_fit_path():
    """The ridge fit separates the per-edge softmax cost from the base
    edge rate when both columns vary, and search_weights folds the
    attention/flat8 weights into the effective edge rate only for
    workloads carrying those flags."""
    m = CM.PartitionCostModel()
    # cold start: the prior already charges attention/flat8 work, so
    # `--partition cost` stops under-balancing them before the first
    # measurement arrives
    wn0, we0 = m.search_weights()
    _, we0a = m.search_weights(attn_edges=True)
    _, we0f = m.search_weights(flat8=True)
    assert we0a == pytest.approx(
        we0 + CM._PRIOR_RAW[CM.PHI.index("attn_edges")])
    assert we0f == pytest.approx(
        we0 + CM._PRIOR_RAW[CM.PHI.index("flat8_chunks")] / 8.0)
    # synthetic truth: 3e-3 ms/k-edge base + 2e-3 ms/k-edge softmax
    # on attention workloads, mixed observations from both kinds
    rng = np.random.RandomState(3)
    for i in range(400):
        phi = np.zeros(len(CM.PHI))
        phi[CM.PHI.index("intercept")] = 1.0
        e = float(rng.randint(128, 1 << 20))
        phi[CM.PHI.index("padded_edges")] = e
        t = 3e-3 * e
        if i % 2:                       # attention workload
            phi[CM.PHI.index("attn_edges")] = e
            t += 2e-3 * e
        m.observe(phi, t)
    w = m.weights_raw()
    assert w[CM.PHI.index("padded_edges")] == pytest.approx(3e-3,
                                                            rel=0.05)
    assert w[CM.PHI.index("attn_edges")] == pytest.approx(2e-3,
                                                          rel=0.05)
    wn, we = m.search_weights()
    _, we_attn = m.search_weights(attn_edges=True)
    assert we == pytest.approx(3e-3, rel=0.05)
    assert we_attn == pytest.approx(5e-3, rel=0.05)
    # flat8: the chunk weight lands per 8-wide sub-row and folds /8
    m2 = CM.PartitionCostModel()
    for _ in range(200):
        phi = np.zeros(len(CM.PHI))
        phi[CM.PHI.index("intercept")] = 1.0
        e = float(rng.randint(1024, 1 << 20))
        phi[CM.PHI.index("padded_edges")] = e
        phi[CM.PHI.index("flat8_chunks")] = e / 8.0
        m2.observe(phi, 3e-3 * e + 8e-3 * (e / 8.0))
    _, we_f = m2.search_weights(flat8=True)
    assert we_f == pytest.approx(3e-3 + 8e-3 / 8.0, rel=0.05)


def test_trainer_phi_flags_follow_workload(dataset):
    """DistributedTrainer threads the workload flags: a GAT on the
    flat8 attention layout charges both columns; a plain GCN charges
    neither."""
    from roc_tpu.models.gat import build_gat
    if len(jax.devices()) < 2:
        pytest.skip("needs the 8-virtual-device rig")
    cfg = TrainConfig(verbose=False, dropout_rate=0.0,
                      aggr_impl="attn_flat8", eval_every=1 << 30)
    tr = DistributedTrainer(
        build_gat([dataset.in_dim, 8, dataset.num_classes], heads=2,
                  dropout_rate=0.0), dataset, 2, cfg)
    assert tr._phi_flags == {"attn_edges": True, "flat8": True}
    phi = tr._phi()
    assert (phi[:, CM.PHI.index("attn_edges")] > 0).all()
    assert (phi[:, CM.PHI.index("flat8_chunks")] > 0).all()
    tr2 = DistributedTrainer(
        build_gcn([dataset.in_dim, 8, dataset.num_classes],
                  dropout_rate=0.0), dataset, 2,
        TrainConfig(verbose=False, dropout_rate=0.0,
                    aggr_impl="segment", eval_every=1 << 30))
    assert tr2._phi_flags == {"attn_edges": False, "flat8": False}
    assert (tr2._phi()[:, CM.PHI.index("attn_edges")] == 0).all()


def test_phi_matrix_and_halo_stats():
    g = synthetic_graph(120, 6, seed=7, power_law=True)
    pg = partition_graph(g, 4, node_multiple=8, edge_multiple=32)
    phi = CM.phi_matrix(pg)
    assert phi.shape == (4, len(CM.PHI))
    assert (phi[:, CM.PHI.index("intercept")] == 1).all()
    # brute-force halo reference from the raw edge list
    halo_in, halo_out = CM.partition_halo_stats(pg)
    dst = g.edge_dst().astype(np.int64)
    src = g.col_idx.astype(np.int64)
    starts = np.asarray([l for l, _ in pg.bounds])
    part_of = np.searchsorted(
        np.asarray([r for _, r in pg.bounds]), np.arange(g.num_nodes))
    cross = part_of[src] != part_of[dst]
    for p in range(4):
        want_in = np.unique(src[cross & (part_of[dst] == p)]).size
        want_out = np.unique(src[cross & (part_of[src] == p)]).size
        assert halo_in[p] == want_in
        assert halo_out[p] == want_out
    # quantized features match the plan's multiples
    np.testing.assert_array_equal(
        phi[:, CM.PHI.index("padded_edges")] % 32, 0)
    stats = CM.partition_static_stats(pg)
    assert stats["num_parts"] == 4
    assert stats["edge_imbalance"] >= 1.0
    assert len(stats["real_edges"]) == 4


# ------------------------------------------- repartition / rebalance

@pytest.fixture(scope="module")
def dataset():
    return synthetic_dataset(96, 7, in_dim=12, num_classes=3, seed=11)


def _skewed_dataset(V=320, seed=4, hubs=(97, 155)):
    """Symmetric hub dataset: two full-star hubs sit where the greedy
    sweep's cap crossings land, so its split is measurably worse than
    the minimax one (~16% modeled max-shard gain at P=2, ~28% at P=4
    with edge_multiple=64) — the repartition trigger fixture."""
    from roc_tpu.core.graph import add_self_edges, from_edge_list
    rng = np.random.RandomState(seed)
    src = rng.randint(0, V, size=800)
    dst = rng.randint(0, V, size=800)
    hsrc = np.concatenate([np.full(V, h) for h in hubs])
    hdst = np.concatenate([np.arange(V) for _ in hubs])
    g = add_self_edges(from_edge_list(
        np.concatenate([src, hsrc]), np.concatenate([dst, hdst]), V,
        symmetrize=True))
    C = 3
    labels = rng.randint(0, C, size=V).astype(np.int32)
    feats = (np.eye(C, dtype=np.float32)[labels]
             .repeat(4, axis=1) + rng.rand(V, 4 * C).astype(np.float32))
    mask = np.full(V, MASK_NONE, dtype=np.int32)
    mask[rng.rand(V) < 0.7] = MASK_TRAIN
    return Dataset(graph=g, features=feats, labels=labels, mask=mask,
                   num_classes=C, name="skewed")


def test_manifest_records_partition_stats(dataset, events):
    cfg = TrainConfig(verbose=False, dropout_rate=0.0,
                      eval_every=1 << 30)
    DistributedTrainer(build_gcn([dataset.in_dim, 8,
                                  dataset.num_classes],
                                 dropout_rate=0.0), dataset, 4, cfg)
    manifests = events.of("manifest")
    assert manifests, "no manifest event"
    part = manifests[-1].get("partition")
    assert part and part["num_parts"] == 4
    assert len(part["real_edges"]) == 4
    assert part["edge_imbalance"] >= 1.0
    # the costmodel imbalance record is emitted too
    cm = events.of("costmodel")
    assert any("partition=" in r["msg"] for r in cm)


def test_repartition_roundtrip_shapes(dataset):
    """A repartition to different bounds with the same quantized
    shapes round-trips every ShardedData shape and keeps training
    running; the compiled step is REUSED (no new compile event)."""
    cfg = TrainConfig(verbose=False, dropout_rate=0.0,
                      eval_every=1 << 30, partition="greedy")
    tr = DistributedTrainer(build_gcn([dataset.in_dim, 8,
                                       dataset.num_classes],
                                      dropout_rate=0.0),
                            dataset, 4, cfg)
    tr.train(epochs=2)
    tr.sync()
    compiled_before = tr._train_step._compiled
    assert compiled_before is not None
    import dataclasses

    def _shapes(data):
        return {
            f.name: jax.tree_util.tree_map(
                lambda a: ((a.shape, str(a.dtype))
                           if hasattr(a, "shape") else a),
                getattr(data, f.name))
            for f in dataclasses.fields(data)}

    shapes_before = _shapes(tr.data)
    old_sig = tr._static_signature(tr.pg, tr.data)
    # nudge one interior boundary by a vertex: different split, and —
    # by construction on this fixture — unchanged padded maxima
    bounds = [list(b) for b in tr.pg.bounds]
    donor = int(np.argmax(tr.pg.real_nodes))
    if donor == 0:
        bounds[0][1] -= 1
        bounds[1][0] -= 1
    else:
        bounds[donor][0] += 1
        bounds[donor - 1][1] += 1
    new_bounds = [tuple(b) for b in bounds]
    plan = plan_from_bounds(dataset.graph.row_ptr, new_bounds, 4,
                            node_multiple=tr.pg.node_multiple,
                            edge_multiple=tr.pg.edge_multiple)
    if (plan.part_nodes, plan.part_edges) != (tr.pg.part_nodes,
                                              tr.pg.part_edges):
        pytest.skip("fixture nudge changed padded maxima")
    tr._repartition(new_bounds)
    assert [tuple(b) for b in tr.pg.bounds] == new_bounds
    assert tr._static_signature(tr.pg, tr.data) == old_sig
    assert _shapes(tr.data) == shapes_before
    tr.train(epochs=2)
    tr.sync()
    # shape-preserving repartition: the SAME AOT executable served the
    # post-repartition steps — no recompile happened
    assert tr._train_step._compiled is compiled_before
    m = tr.evaluate()
    assert np.isfinite(m["train_loss"])


def test_repartition_recompiles_on_shape_change(dataset, events):
    """Changed quantized shapes must rebuild the observed steps (stale
    trace-time constants would silently mis-aggregate) — asserted via
    fresh compile-observer events."""
    cfg = TrainConfig(verbose=False, dropout_rate=0.0,
                      eval_every=1 << 30, partition="greedy")
    tr = DistributedTrainer(build_gcn([dataset.in_dim, 8,
                                       dataset.num_classes],
                                      dropout_rate=0.0),
                            dataset, 4, cfg)
    tr.train(epochs=1)
    tr.sync()
    n_compiles = len([r for r in events.of("compile")
                      if r.get("name") == "dist_train_step"])
    # an extreme split (everything in part 0) must change part_edges
    V = dataset.graph.num_nodes
    lop = [(0, V - 3), (V - 2, V - 2), (V - 1, V - 1), (V, V - 1)]
    tr._repartition(lop)
    assert tr._loop_compiled is False
    tr.train(epochs=1)
    tr.sync()
    got = len([r for r in events.of("compile")
               if r.get("name") == "dist_train_step"])
    assert got == n_compiles + 1
    assert np.isfinite(tr.evaluate()["train_loss"])


@pytest.mark.parametrize("num_parts", [2, 4])
def test_rebalance_parity_with_never_repartition(num_parts, events):
    """Repartition-enabled training matches the never-repartition run
    to <= 1e-5 (full-batch training is split-invariant): same init,
    same data, greedy start — the rebalance run upgrades to the cost
    split at the first eval and must land on the same parameters."""
    ds = _skewed_dataset()
    kw = dict(verbose=False, dropout_rate=0.0, weight_decay=1e-3,
              learning_rate=0.01, eval_every=2, epochs=8, chunk=64,
              partition="greedy")
    ref = DistributedTrainer(build_gcn([ds.in_dim, 8, ds.num_classes],
                                       dropout_rate=0.0), ds,
                             num_parts, TrainConfig(**kw))
    reb = DistributedTrainer(build_gcn([ds.in_dim, 8, ds.num_classes],
                                       dropout_rate=0.0), ds,
                             num_parts,
                             TrainConfig(rebalance=True,
                                         rebalance_gain=0.005, **kw))
    ref.train()
    reb.train()
    assert reb._rebalances >= 1, \
        "fixture produced no repartition — parity claim untested"
    assert any("repartition #" in r["msg"]
               for r in events.of("costmodel"))
    for k in ref.params:
        np.testing.assert_allclose(np.asarray(ref.params[k]),
                                   np.asarray(reb.params[k]),
                                   rtol=1e-5, atol=1e-5)
    m_ref, m_reb = ref.evaluate(), reb.evaluate()
    np.testing.assert_allclose(m_ref["train_loss"],
                               m_reb["train_loss"],
                               rtol=1e-5, atol=1e-5)


def test_rebalance_hysteresis_caps_repartitions(events):
    """<= rebalance_max repartitions per run, and a converged split
    stops moving (gain under the threshold)."""
    ds = _skewed_dataset(seed=9)
    cfg = TrainConfig(verbose=False, dropout_rate=0.0,
                      weight_decay=1e-3, eval_every=1, epochs=10,
                      chunk=64, partition="greedy", rebalance=True,
                      rebalance_gain=0.005, rebalance_max=2)
    tr = DistributedTrainer(build_gcn([ds.in_dim, 8, ds.num_classes],
                                      dropout_rate=0.0), ds, 4, cfg)
    tr.train()
    assert tr._rebalances <= 2
    # with the cost split in place, another search under the same
    # weights is a no-op — the hysteresis event trail records it
    assert any("keeping the current split" in r["msg"]
               or "repartition #" in r["msg"]
               for r in events.of("costmodel"))


def test_rebalance_rejects_injected_data(dataset):
    from roc_tpu.parallel.distributed import make_mesh, shard_dataset
    pg = partition_graph(dataset.graph, 4, node_multiple=8,
                         edge_multiple=512)
    mesh = make_mesh(4)
    data = shard_dataset(dataset, pg, mesh)
    cfg = TrainConfig(verbose=False, rebalance=True)
    with pytest.raises(ValueError, match="rebalance"):
        DistributedTrainer(build_gcn([dataset.in_dim, 8,
                                      dataset.num_classes]),
                           dataset, 4, cfg, data=data, pg=pg)


def test_distributed_cost_partition_matches_single_device(dataset):
    """The default 'auto' (= cost) split trains to the same result as
    the single-device reference — partition-count invariance holds
    for the new split exactly as it did for greedy."""
    from roc_tpu.train.trainer import Trainer
    model = build_gcn([dataset.in_dim, 16, dataset.num_classes],
                      dropout_rate=0.0)
    kw = dict(dropout_rate=0.0, verbose=False, epochs=8,
              weight_decay=1e-3, learning_rate=0.01)
    single = Trainer(model, dataset, TrainConfig(**kw))
    dist = DistributedTrainer(model, dataset, 4,
                              TrainConfig(partition="cost", **kw))
    assert dist._partition_method == "cost"
    single.train()
    dist.train()
    for k in single.params:
        np.testing.assert_allclose(np.asarray(single.params[k]),
                                   np.asarray(dist.params[k]),
                                   rtol=2e-4, atol=2e-5)
