"""Model builder + single-device training: convergence on the synthetic
fixture (the reference's correctness-by-convergence strategy, SURVEY §4),
plus parity checks on the layer stack and aggregation-impl invariance."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu.core.graph import synthetic_dataset
from roc_tpu.models.gcn import build_gcn
from roc_tpu.train.trainer import TrainConfig, Trainer, make_graph_context


@pytest.fixture(scope="module")
def dataset():
    return synthetic_dataset(128, 8, in_dim=16, num_classes=4, seed=0)


def test_gcn_forward_shapes(dataset):
    model = build_gcn([dataset.in_dim, 32, dataset.num_classes])
    gctx = make_graph_context(dataset)
    params = model.init_params(jax.random.PRNGKey(0))
    logits = model.apply(params, jnp.asarray(dataset.features), gctx,
                         train=False)
    assert logits.shape == (dataset.graph.num_nodes, dataset.num_classes)
    assert np.isfinite(np.asarray(logits)).all()


def test_glorot_init_range(dataset):
    model = build_gcn([dataset.in_dim, 32, dataset.num_classes])
    params = model.init_params(jax.random.PRNGKey(0))
    w = np.asarray(params["linear_0"])
    s = np.sqrt(6.0 / (dataset.in_dim + 32))
    assert w.shape == (dataset.in_dim, 32)
    assert (np.abs(w) <= s).all()
    assert w.std() > 0.3 * s  # actually uniform, not degenerate


def test_residual_path_built():
    # >3 layer entries => residual linears are added (gnn.cc:86-90)
    m_small = build_gcn([8, 4, 3])
    m_deep = build_gcn([8, 16, 16, 3])
    n_lin_small = sum(1 for op in m_small._ops if op.kind == "linear")
    n_lin_deep = sum(1 for op in m_deep._ops if op.kind == "linear")
    assert n_lin_small == 2
    assert n_lin_deep == 6  # 3 main + 3 residual projections


def test_training_converges(dataset):
    model = build_gcn([dataset.in_dim, 32, dataset.num_classes],
                      dropout_rate=0.1)
    cfg = TrainConfig(learning_rate=0.01, weight_decay=1e-4,
                      epochs=60, verbose=False, eval_every=5)
    trainer = Trainer(model, dataset, cfg)
    history = trainer.train()
    first, last = history[0], history[-1]
    assert last["train_acc"] > 0.9
    assert last["test_acc"] > 0.75
    assert last["train_loss"] < first["train_loss"]


def test_aggr_impl_invariance(dataset):
    """segment vs blocked produce the same logits (same weights, no
    dropout)."""
    model = build_gcn([dataset.in_dim, 32, dataset.num_classes])
    params = model.init_params(jax.random.PRNGKey(1))
    feats = jnp.asarray(dataset.features)
    logits = {}
    for impl in ("segment", "blocked", "ell"):
        gctx = make_graph_context(dataset, aggr_impl=impl, chunk=256)
        logits[impl] = np.asarray(
            model.apply(params, feats, gctx, train=False))
    np.testing.assert_allclose(logits["segment"], logits["blocked"],
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(logits["segment"], logits["ell"],
                               rtol=1e-4, atol=1e-4)


def test_symmetric_vjp_matches_autodiff(dataset):
    """The custom backward (reference kernel-reuse, valid for symmetric
    graphs) must equal exact autodiff through the forward."""
    import dataclasses
    model = build_gcn([dataset.in_dim, 16, dataset.num_classes])
    params = model.init_params(jax.random.PRNGKey(3))
    feats = jnp.asarray(dataset.features)
    labels = jnp.asarray(dataset.labels)
    mask = jnp.asarray(dataset.mask)
    gctx_sym = make_graph_context(dataset)
    gctx_exact = dataclasses.replace(gctx_sym, symmetric=False)

    def loss(p, gctx):
        l, _ = model.loss_fn(p, feats, labels, mask, gctx, train=False)
        return l

    g_sym = jax.grad(loss)(params, gctx_sym)
    g_exact = jax.grad(loss)(params, gctx_exact)
    for k in g_sym:
        np.testing.assert_allclose(np.asarray(g_sym[k]),
                                   np.asarray(g_exact[k]),
                                   rtol=1e-4, atol=1e-5)


def test_deterministic_training(dataset):
    model = build_gcn([dataset.in_dim, 16, dataset.num_classes])
    cfg = TrainConfig(epochs=5, verbose=False, seed=7)
    t1 = Trainer(model, dataset, cfg)
    t2 = Trainer(model, dataset, cfg)
    t1.train()
    t2.train()
    for k in t1.params:
        np.testing.assert_array_equal(np.asarray(t1.params[k]),
                                      np.asarray(t2.params[k]))


def test_lr_decay_schedule():
    from roc_tpu.train.optimizer import decayed_lr
    lr0 = float(decayed_lr(0.01, jnp.asarray(0), 0.97, 100))
    lr100 = float(decayed_lr(0.01, jnp.asarray(100), 0.97, 100))
    lr250 = float(decayed_lr(0.01, jnp.asarray(250), 0.97, 100))
    assert lr0 == pytest.approx(0.01, rel=1e-5)
    assert lr100 == pytest.approx(0.01 * 0.97, rel=1e-5)
    assert lr250 == pytest.approx(0.01 * 0.97 ** 2, rel=1e-5)


def test_adam_matches_reference_formula():
    """One Adam step on a scalar parameter, hand-computed with the
    reference recurrence (optimizer_kernel.cu:52-62, optimizer.cc:79-85)."""
    from roc_tpu.train.optimizer import AdamConfig, adam_init, adam_update
    params = {"w": jnp.asarray([2.0], dtype=jnp.float32)}
    grads = {"w": jnp.asarray([0.5], dtype=jnp.float32)}
    cfg = AdamConfig(weight_decay=0.1)
    st = adam_init(params)
    new_p, st2 = adam_update(params, grads, st, jnp.asarray(0.01), cfg)

    gt = 0.5 + 0.1 * 2.0
    mt = 0.1 * gt
    vt = 0.001 * gt * gt
    alpha_t = 0.01 * np.sqrt(1 - 0.999) / (1 - 0.9)
    want = 2.0 - alpha_t * mt / (np.sqrt(vt) + 1e-8)
    np.testing.assert_allclose(np.asarray(new_p["w"])[0], want, rtol=1e-6)


def test_remat_policies_train_identically():
    """remat with either policy must produce the same parameters as
    no-remat (checkpointing changes memory, not math)."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer
    ds = synthetic_dataset(200, 6, in_dim=12, num_classes=3, seed=9)
    results = {}
    for name, kw in [("none", dict(remat=False)),
                     ("full", dict(remat=True, remat_policy="full")),
                     ("save_agg", dict(remat=True,
                                       remat_policy="save_aggregates"))]:
        model = build_gcn([12, 8, 3], dropout_rate=0.0)
        cfg = TrainConfig(learning_rate=0.05, epochs=3,
                          eval_every=1 << 30, verbose=False,
                          symmetric=True, **kw)
        tr = Trainer(model, ds, cfg)
        tr.train()
        results[name] = tr.params
    for k in results["none"]:
        np.testing.assert_allclose(np.asarray(results["none"][k]),
                                   np.asarray(results["full"][k]),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(results["none"][k]),
                                   np.asarray(results["save_agg"][k]),
                                   rtol=1e-5, atol=1e-5)
