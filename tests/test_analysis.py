"""roc-lint static analyzer (roc_tpu/analysis): every rule fires on a
synthetic violation, the tree itself is clean modulo the baseline, and
the CLI gate is wired into the tier (the lint_prints.sh successor)."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from roc_tpu.analysis.ast_lint import run_ast_lint
from roc_tpu.analysis.findings import (Finding, dedupe, load_baseline,
                                       save_baseline, shrink_baseline,
                                       split_findings)
from roc_tpu.analysis.hlo_lint import check_bytes_model, check_large_copy
from roc_tpu.analysis.jaxpr_lint import JaxprUnit, run_jaxpr_lint

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _plant(root, relpath, text):
    p = root / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(text)


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------- AST fixtures

def test_stdout_print_fires_and_allows(tmp_path):
    _plant(tmp_path, "roc_tpu/mod.py",
           "import sys\n"
           "print('leak')\n"
           "print('err', file=sys.stderr)\n"
           "print(format_metrics(1, {}))\n")
    got = run_ast_lint(str(tmp_path), select=["stdout-print"])
    assert [(f.rule, f.line) for f in got] == [("stdout-print", 2)]


def test_host_sync_hot_path_fires(tmp_path):
    _plant(tmp_path, "roc_tpu/ops/hot.py",
           "import jax\n"
           "def f(x, rate):\n"
           "    a = jax.device_get(x)\n"
           "    b = x.sum().item()\n"
           "    c = float(x.sum())\n"
           "    d = float(rate)\n"          # plain name: allowed
           "    # host-side numpy: roc-lint: ok=host-sync-hot-path\n"
           "    e = jax.device_get(x)\n"    # pragma'd: allowed
           "    return a, b, c, d, e\n")
    # the same code OUTSIDE a hot-path module is not flagged
    _plant(tmp_path, "roc_tpu/cold.py",
           "import jax\n"
           "def f(x):\n"
           "    return float(x.sum())\n")
    got = run_ast_lint(str(tmp_path), select=["host-sync-hot-path"])
    assert [f.line for f in got] == [3, 4, 5]
    assert all(f.unit == "roc_tpu/ops/hot.py" for f in got)


def test_sync_h2d_in_loop_fires(tmp_path):
    _plant(tmp_path, "roc_tpu/core/streaming.py",
           "import jax\n"
           "import numpy as np\n"
           "def stage_once(feats, lo, hi):\n"
           "    # outside any loop: the sanctioned pool call site\n"
           "    return jax.device_put(np.ascontiguousarray("
           "feats[lo:hi]))\n"
           "def bad(blocks):\n"
           "    out = []\n"
           "    for b in blocks:\n"
           "        x = np.ascontiguousarray(b)\n"
           "        out.append(jax.device_put(x))\n"
           "    i = 0\n"
           "    while i < 3:\n"
           "        # cold loop: roc-lint: ok=sync-h2d-in-loop\n"
           "        jax.device_put(blocks[i])\n"
           "        i += 1\n"
           "    comp = [jax.device_put(b) for b in blocks]\n"
           "    return out, comp\n")
    # the same calls OUTSIDE the hot modules are not this rule's
    # business
    _plant(tmp_path, "roc_tpu/train/cold.py",
           "import jax\n"
           "def f(bs):\n"
           "    return [jax.device_put(b) for b in bs]\n")
    got = run_ast_lint(str(tmp_path), select=["sync-h2d-in-loop"])
    # the for-body copy + put, and the comprehension rewrite (the
    # obvious ratchet dodge) — the pragma'd while body stays quiet
    assert [(f.rule, f.line) for f in got] == \
        [("sync-h2d-in-loop", 9), ("sync-h2d-in-loop", 10),
         ("sync-h2d-in-loop", 16)]
    assert all(f.unit == "roc_tpu/core/streaming.py" for f in got)


def test_dequant_hot_path_fires(tmp_path):
    """PR-19 rule: a float32 materialization of a tableish value
    inside roc_tpu/serve/ (astype, asarray(dtype=), or a float32()
    cast) is a finding — the dequantize must stay fused in-register
    — while the pragma'd sanctioned site and non-table values stay
    quiet, and serve-external code is not this rule's business."""
    _plant(tmp_path, "roc_tpu/serve/hot.py",
           "import jax.numpy as jnp\n"
           "import numpy as np\n"
           "def f(q_table, stage0, ids, x):\n"
           "    a = q_table.astype(jnp.float32)\n"
           "    b = np.asarray(stage0, dtype=np.float32)\n"
           "    c = jnp.float32(q_table)\n"
           "    d = x.astype(jnp.float32)\n"          # not tableish
           "    # export-time: roc-lint: ok=dequant-hot-path\n"
           "    e = q_table.astype(jnp.float32)\n"
           "    return a, b, c, d, e\n")
    _plant(tmp_path, "roc_tpu/core/cold.py",
           "import numpy as np\n"
           "def f(table):\n"
           "    return np.asarray(table, dtype=np.float32)\n")
    got = run_ast_lint(str(tmp_path), select=["dequant-hot-path"])
    assert [(f.rule, f.line) for f in got] == \
        [("dequant-hot-path", 4), ("dequant-hot-path", 5),
         ("dequant-hot-path", 6)]
    assert all(f.unit == "roc_tpu/serve/hot.py" for f in got)


def test_bare_jit_fires_and_observed_form_allowed(tmp_path):
    _plant(tmp_path, "roc_tpu/train/steps.py",
           "import jax\n"
           "from roc_tpu.obs.compile_watch import ObservedJit\n"
           "def build(fn):\n"
           "    bad = jax.jit(fn)\n"
           "    good = ObservedJit(jitfn=jax.jit(fn), name='s')\n"
           "    return bad, good\n")
    got = run_ast_lint(str(tmp_path), select=["bare-jit"])
    assert [(f.rule, f.line) for f in got] == [("bare-jit", 4)]


def test_pallas_interpret_fires(tmp_path):
    _plant(tmp_path, "roc_tpu/kernels/k.py",
           "from jax.experimental import pallas as pl\n"
           "def run(body, shape, interpret=False):\n"
           "    bad = pl.pallas_call(body, out_shape=shape)\n"
           "    good = pl.pallas_call(body, out_shape=shape,\n"
           "                          interpret=interpret)\n"
           "    return bad, good\n")
    got = run_ast_lint(str(tmp_path), select=["pallas-interpret"])
    assert [(f.rule, f.line) for f in got] == [("pallas-interpret", 3)]


def test_swallowed_exception_fires_and_allows(tmp_path):
    """Recovery/streaming/checkpoint paths: bare except (any body)
    and except-with-pass-only body both fire; a handler that handles
    (or re-raises) is clean, the pragma suppresses, and the same code
    OUTSIDE the scoped paths is not flagged."""
    code = ("import os\n"
            "def f(p):\n"
            "    try:\n"
            "        os.remove(p)\n"
            "    except:\n"                               # line 5
            "        print('x', file=None)\n"
            "    try:\n"
            "        os.remove(p)\n"
            "    except OSError:\n"                       # line 9
            "        pass\n"
            "    try:\n"
            "        os.remove(p)\n"
            "    except OSError as e:\n"
            "        raise RuntimeError('ctx') from e\n"  # handled: ok
            "    try:\n"
            "        os.remove(p)\n"
            "    # why: roc-lint: ok=swallowed-exception\n"
            "    except OSError:\n"                       # pragma'd
            "        pass\n")
    _plant(tmp_path, "roc_tpu/resilience/rec.py", code)
    _plant(tmp_path, "roc_tpu/ops/cold.py", code)  # out of scope
    got = run_ast_lint(str(tmp_path), select=["swallowed-exception"])
    assert [(f.rule, f.unit, f.line) for f in got] == [
        ("swallowed-exception", "roc_tpu/resilience/rec.py", 5),
        ("swallowed-exception", "roc_tpu/resilience/rec.py", 9)]


def test_event_clock_fires_and_allows(tmp_path):
    """event-clock: hand-passed reserved clock kwargs on emit() and
    hand-rolled event dicts (cat+msg literals) both fire; normal emit
    calls, non-event dicts, the bus module itself, and the pragma are
    all clean."""
    code = ("from roc_tpu.obs.events import emit\n"
            "def f(bus):\n"
            "    emit('epoch', 'ok', epoch=1)\n"            # clean
            "    emit('epoch', 'bad', t=123.0)\n"           # line 4
            "    bus.emit('run', 'bad2', proc=3, host='h')\n"  # line 5
            "    rec = {'cat': 'epoch', 'msg': 'handrolled'}\n"  # 6
            "    ok = {'cat': 'span'}\n"                    # clean
            "    ok2 = {'msg': 'x', 'name': 'y'}\n"         # clean
            "    emit('epoch', 'sup', t=1.0)  "
            "# why: roc-lint: ok=event-clock\n"
            "    return rec, ok, ok2\n")
    _plant(tmp_path, "roc_tpu/train/mod.py", code)
    # the bus module itself legitimately builds the stamped record
    _plant(tmp_path, "roc_tpu/obs/events.py",
           "def emit(cat, msg, **f):\n"
           "    return {'t': 0.0, 'cat': cat, 'msg': msg, **f}\n")
    got = run_ast_lint(str(tmp_path), select=["event-clock"])
    assert [(f.rule, f.unit, f.line) for f in got] == [
        ("event-clock", "roc_tpu/train/mod.py", 4),
        ("event-clock", "roc_tpu/train/mod.py", 5),
        ("event-clock", "roc_tpu/train/mod.py", 6)]


def test_event_clock_registered_and_tree_clean():
    from roc_tpu.analysis.driver import all_rule_names, is_trace_rule
    assert "event-clock" in all_rule_names()
    assert not is_trace_rule("event-clock")
    # ratchet bites from zero on the real tree: no unbaselined finding
    got = run_ast_lint(_REPO, select=["event-clock"])
    assert got == [], [(f.unit, f.line, f.msg) for f in got]


def test_metric_adhoc_fires_and_allows(tmp_path):
    """metric-adhoc (PR 17): serve/train hot paths must record
    through the metrics registry — an ad-hoc ``self._n_* +=``
    counter and a ``*_ms``/``*_lat`` ``.append`` both fire; registry
    calls, non-metric attributes, the pragma, and the same code
    OUTSIDE the scoped paths are all clean."""
    code = ("class S:\n"
            "    def hot(self, ms):\n"
            "        self._n_shed += 1\n"                  # line 3
            "        self.lat_ms.append(ms)\n"            # line 4
            "        self._h_batch.record(ms)\n"          # registry: ok
            "        self._c_shed.inc()\n"                # registry: ok
            "        self.rows.append(ms)\n"              # not *_ms: ok
            "        # span buffer: roc-lint: ok=metric-adhoc\n"
            "        self.laps_ms.append(ms)\n")          # pragma'd
    _plant(tmp_path, "roc_tpu/serve/mod.py", code)
    _plant(tmp_path, "roc_tpu/train/trainer.py", code)
    _plant(tmp_path, "roc_tpu/ops/cold.py", code)  # out of scope
    got = run_ast_lint(str(tmp_path), select=["metric-adhoc"])
    assert [(f.rule, f.unit, f.line) for f in got] == [
        ("metric-adhoc", "roc_tpu/serve/mod.py", 3),
        ("metric-adhoc", "roc_tpu/serve/mod.py", 4),
        ("metric-adhoc", "roc_tpu/train/trainer.py", 3),
        ("metric-adhoc", "roc_tpu/train/trainer.py", 4)]


def test_metric_adhoc_registered_and_tree_clean():
    """The rule rides the shrink-only baseline ratchet from zero: the
    real serve/ + trainer hot paths carry no unpragma'd ad-hoc
    metric sites (the sanctioned timer-lap buffers carry the
    documented pragma)."""
    from roc_tpu.analysis.driver import all_rule_names, is_trace_rule
    assert "metric-adhoc" in all_rule_names()
    assert not is_trace_rule("metric-adhoc")
    got = run_ast_lint(_REPO, select=["metric-adhoc"])
    assert got == [], [(f.unit, f.line, f.msg) for f in got]


# ----------------------------------------------------- jaxpr fixtures

def _unit(fn, *args, name="fix", **ctx):
    ctx.setdefault("num_nodes", 64)
    ctx.setdefault("vf_elems", 64 * 16)
    return JaxprUnit(name, jax.make_jaxpr(fn)(*args), **ctx)


def test_jaxpr_f32_upcast_fires_only_in_bf16_path():
    x = jnp.ones((64, 16), jnp.bfloat16)
    u = _unit(lambda a: a.astype(jnp.float32) * 2.0, x,
              compute_dtype="bfloat16")
    got = run_jaxpr_lint([u], select=["jaxpr-f32-upcast"])
    assert _rules(got) == ["jaxpr-f32-upcast"]
    # class-width tensors ([V, C], C << F) stay sanctioned
    small = jnp.ones((64, 4), jnp.bfloat16)
    u2 = _unit(lambda a: a.astype(jnp.float32) * 2.0, small,
               compute_dtype="bfloat16")
    assert not run_jaxpr_lint([u2], select=["jaxpr-f32-upcast"])
    # and an fp32-configured path never arms the rule
    u3 = _unit(lambda a: a.astype(jnp.float32) * 2.0, x,
               compute_dtype="float32")
    assert not run_jaxpr_lint([u3], select=["jaxpr-f32-upcast"])


def test_jaxpr_host_callback_fires():
    def f(x):
        jax.debug.print("x sum {}", x.sum())
        return x * 2
    u = _unit(f, jnp.ones(8))
    got = run_jaxpr_lint([u], select=["jaxpr-host-callback"])
    assert _rules(got) == ["jaxpr-host-callback"]
    assert "debug_callback" in got[0].msg


def test_jaxpr_non_donated_fires_on_update_shaped_arg():
    big = jnp.ones((256, 64))
    other = jnp.ones((128, 32))

    def f(a, b):
        return a + 1.0, b.sum()

    u = _unit(jax.jit(f), big, other, donate_min_bytes=1024)
    got = run_jaxpr_lint([u], select=["jaxpr-non-donated"])
    # a's aval matches output 0 and is undonated; b's matches nothing
    # (the matching is aval-level, so distinct shapes isolate it)
    assert len(got) == 1 and "arg 0" in got[0].msg
    # donated: clean
    u2 = _unit(jax.jit(f, donate_argnums=(0,)), big, other,
               donate_min_bytes=1024)
    assert not run_jaxpr_lint([u2], select=["jaxpr-non-donated"])


def test_jaxpr_non_donated_value_and_grad_recognized():
    """The rule's one known false positive, fixed at the rule (the
    retired tail_grad baseline entry): a (scalar value, grads...)
    jaxpr's grad-shaped output is a COTANGENT of its primal argument,
    not an update of it — the caller still needs the primal for the
    optimizer apply, so donation is not the fix."""
    w = jnp.ones((64, 32))

    def value_and_grad_step(params, x):
        return jax.value_and_grad(
            lambda p: (x @ p).sum())(params)

    u = _unit(jax.jit(value_and_grad_step), w, jnp.ones((16, 64)),
              donate_min_bytes=1024)
    assert not run_jaxpr_lint([u], select=["jaxpr-non-donated"])

    # an update-style step (no leading scalar) is judged as before
    def update_step(params, x):
        g = jax.grad(lambda p: (x @ p).sum())(params)
        return params - 0.1 * g

    u2 = _unit(jax.jit(update_step), w, jnp.ones((16, 64)),
               donate_min_bytes=1024)
    got = run_jaxpr_lint([u2], select=["jaxpr-non-donated"])
    assert len(got) == 1 and "arg 0" in got[0].msg

    # value-and-grad whose PRIMAL arg also matches the scalar-first
    # output list via a LATER output is still exempt, but one that
    # echoes an arg as output 0's aval is not value-and-grad shaped
    def echo_first(params, x):
        return params * 2.0, (x @ params).sum()

    u3 = _unit(jax.jit(echo_first), w, jnp.ones((16, 64)),
               donate_min_bytes=1024)
    assert run_jaxpr_lint([u3], select=["jaxpr-non-donated"])


def test_jaxpr_non_donated_scalar_first_param_update_still_fires():
    """A scalar PARAM that flattens first (learned-eps style) must not
    disarm the rule for an update step: the echoed output prefix
    (scalar head + first weight) mirrors the input prefix in order,
    which value_and_grad's (loss, cotangents...) never does unless the
    primal's first TWO leaves are scalar."""
    params = {"eps": jnp.ones(()), "w": jnp.ones((64, 32))}

    def update_step(params, x):
        g = jax.grad(
            lambda p: ((x @ p["w"]).sum() * p["eps"]))(params)
        return jax.tree_util.tree_map(lambda pp, gg: pp - 0.1 * gg,
                                      params, g)

    u = _unit(jax.jit(update_step), params, jnp.ones((16, 64)),
              donate_min_bytes=1024)
    got = run_jaxpr_lint([u], select=["jaxpr-non-donated"])
    assert len(got) == 1 and "[64, 32]" in got[0].msg

    # ...while value_and_grad over the SAME scalar-first params keeps
    # its exemption (output 1 is the scalar's cotangent, which does
    # not track input leaf 1)
    def vag_step(params, x):
        return jax.value_and_grad(
            lambda p: ((x @ p["w"]).sum() * p["eps"]))(params)

    u2 = _unit(jax.jit(vag_step), params, jnp.ones((16, 64)),
               donate_min_bytes=1024)
    assert not run_jaxpr_lint([u2], select=["jaxpr-non-donated"])


def test_baseline_is_empty():
    """The tree lints clean with an EMPTY findings baseline — the last
    entry (the tail_grad value-and-grad false positive) is retired at
    the rule, not absorbed."""
    data = json.load(open(
        os.path.join(_REPO, "scripts", "lint_baseline.json")))
    assert data["findings"] == []


def test_jaxpr_collective_materialize_fires():
    from jax.sharding import Mesh, PartitionSpec as P
    from roc_tpu.parallel.distributed import _shard_map
    mesh = Mesh(np.asarray(jax.devices()), ("parts",))
    x = jnp.ones((64, 16))

    def body(xb):
        full = jax.lax.all_gather(xb, "parts", axis=0, tiled=True)
        return jax.lax.psum(full, "parts")

    sm = _shard_map(body, mesh, P("parts"), P())
    parts = len(jax.devices())
    # shard_map body avals are block-local: vf_elems is PER-DEVICE
    per_dev = (64 * 16) // parts
    u = _unit(jax.jit(sm), x, halo="gather", vf_elems=per_dev,
              mesh_parts=parts)
    got = run_jaxpr_lint([u], select=["jaxpr-collective-materialize"])
    # the psum of the FULL gathered [V, F] fires; the whole-region
    # gather itself is the designed halo and stays sanctioned
    assert len(got) == 1 and "psum" in got[0].msg
    # under halo='ring' the [V, F] gather itself is also a violation
    u2 = _unit(jax.jit(sm), x, halo="ring", vf_elems=per_dev,
               mesh_parts=parts)
    got2 = run_jaxpr_lint([u2],
                          select=["jaxpr-collective-materialize"])
    assert len(got2) == 2
    assert any("ring" in f.msg for f in got2)


def test_jaxpr_int32_overflow_fires():
    def f():
        idx = jax.lax.iota(jnp.int32, 1 << 16)
        return idx * jnp.int32(1 << 16)      # bound ~2^32 in int32

    got = run_jaxpr_lint([_unit(f)], select=["jaxpr-int32-overflow"])
    assert _rules(got) == ["jaxpr-int32-overflow"]
    assert "mul" in got[0].msg

    def ok():
        idx = jax.lax.iota(jnp.int32, 1 << 16)
        return idx * jnp.int32(4)

    assert not run_jaxpr_lint([_unit(ok)],
                              select=["jaxpr-int32-overflow"])


# ------------------------------------------------------- HLO fixtures

_HLO = """\
ENTRY %main.1 (p0: f32[512,128]) -> f32[512,128] {
  %big = f32[512,128]{0,1} transpose(f32[512,128]{1,0} %p0)
  %tiny = f32[8,4]{0,1} transpose(f32[4,8]{1,0} %q)
  ROOT %r = f32[512,128]{1,0} copy(f32[512,128]{0,1} %big)
}
%fused_computation.2 (param_0: f32[512,128]) -> f32[512,128] {
  %infused = f32[512,128]{1,0} copy(f32[512,128]{0,1} %param_0)
}
"""


def test_hlo_large_copy_fires_outside_fusions():
    got = check_large_copy("hlo:fix", _HLO, copy_min_elems=512 * 128)
    ops = sorted(f.key.split("|")[0] for f in got)
    # the entry transpose + copy; the fused-body copy and the tiny
    # transpose stay silent
    assert ops == ["copy", "transpose"]


def test_hlo_bytes_model_fires_past_factor():
    got = check_bytes_model("hlo:fix", 1e9, 1000, factor=32.0)
    assert _rules(got) == ["hlo-bytes-model"]
    assert not check_bytes_model("hlo:fix", 3.1e4, 1000, factor=32.0)
    # missing introspection is not a finding
    assert not check_bytes_model("hlo:fix", None, 1000)
    assert not check_bytes_model("hlo:fix", 1e9, None)


# -------------------------------------------- built-trainer fixtures

def test_partition_imbalance_rule():
    """[partition-imbalance] fires past max/mean 1.5 on >1 device,
    stays silent on balanced splits and single devices, and carries a
    ratchetable fingerprint."""
    from roc_tpu.analysis.driver import check_partition_imbalance
    got = check_partition_imbalance("partition:fix",
                                    [100, 10, 10, 10])
    assert len(got) == 1
    assert got[0].rule == "partition-imbalance"
    assert "3.08" in got[0].msg
    assert got[0].fingerprint == \
        "partition-imbalance|partition:fix|parts=4"
    # balanced: quiet
    assert not check_partition_imbalance("partition:fix",
                                         [10, 11, 10, 10])
    # single device: the straggler IS the device — not a finding
    assert not check_partition_imbalance("partition:fix", [100])
    # empty / zero-edge degenerate inputs never divide by zero
    assert not check_partition_imbalance("partition:fix", [])
    assert not check_partition_imbalance("partition:fix", [0, 0])


def test_partition_imbalance_registered():
    from roc_tpu.analysis.driver import all_rule_names, is_trace_rule
    assert "partition-imbalance" in all_rule_names()
    assert is_trace_rule("partition-imbalance")
    assert is_trace_rule("jaxpr-f32-upcast")
    assert not is_trace_rule("stdout-print")


# ------------------------------------------------- baseline mechanics

def test_baseline_split_and_shrink_only(tmp_path):
    bp = str(tmp_path / "baseline.json")
    save_baseline(bp, ["r|u|a", "r|u|gone"])
    findings = [Finding("r", "u", "m", key="a"),
                Finding("r", "u", "m", key="new")]
    new, old, stale = split_findings(findings, load_baseline(bp))
    assert [f.key for f in new] == ["new"]
    assert [f.key for f in old] == ["a"]
    assert stale == {"r|u|gone"}
    # the ratchet can only shrink: the stale entry is dropped, the new
    # finding is NOT absorbed
    kept = shrink_baseline(bp, findings)
    assert kept == {"r|u|a"}
    assert load_baseline(bp) == {"r|u|a"}


def test_dedupe_keeps_first():
    fs = [Finding("r", "u", "m", key="k"), Finding("r", "u", "m2",
                                                   key="k")]
    assert len(dedupe(fs)) == 1


# ----------------------------------------------- tree + tier wiring

def test_tree_has_zero_unbaselined_findings():
    """Both trainers' step jaxprs (single + 8-virtual-device mesh),
    the model graph, the compiled HLO, and the whole source tree:
    clean modulo scripts/lint_baseline.json."""
    from roc_tpu.analysis.driver import analyze
    findings = analyze(_REPO)
    baseline = load_baseline(
        os.path.join(_REPO, "scripts", "lint_baseline.json"))
    new, _, _ = split_findings(findings, baseline)
    assert not new, "\n".join(f.render() for f in new)


def test_cli_strict_gate():
    """The tier gate: `python -m roc_tpu.analysis --strict` exits 0
    on the tree inside the <90 s CPU budget with all six levels
    (AST/concurrency/jaxpr/HLO/programspace/collective) enabled
    (lint_prints.sh's
    successor — tests/test_obs.py keeps the wrapper covered), and the
    pre-flight budget lines scripts/test.sh surfaces are printed."""
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "roc_tpu.analysis", "--strict"],
        cwd=_REPO, capture_output=True, text=True, timeout=90,
        env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 new" in r.stdout
    assert "program budget gin_flat8:" in r.stdout
    assert "program budget sgc_stream:" in r.stdout


def test_cli_ratchet_bites(tmp_path):
    """A planted violation in a scratch tree fails the CLI."""
    _plant(tmp_path, "roc_tpu/leaky.py", "print('oops stdout')\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "roc_tpu.analysis",
         "--root", str(tmp_path), "--select", "stdout-print"],
        capture_output=True, text=True, timeout=60, env=env)
    assert r.returncode == 1
    assert "leaky.py:1" in r.stdout


def test_cli_update_baseline_shrinks_never_absorbs(tmp_path):
    _plant(tmp_path, "roc_tpu/leaky.py", "print('oops stdout')\n")
    bp = tmp_path / "scripts" / "lint_baseline.json"
    bp.parent.mkdir()
    bp.write_text(json.dumps(
        {"version": 1,
         "findings": ["jaxpr-non-donated|jaxpr:t|y",
                      "stdout-print|gone|x"]}))
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "roc_tpu.analysis",
         "--root", str(tmp_path), "--select", "stdout-print",
         "--update-baseline"],
        capture_output=True, text=True, timeout=60, env=env)
    # the stale entry of the rule that RAN is dropped; the trace-rule
    # entry is untouched (its rule never ran in this --select pass);
    # the live violation is NOT absorbed -> still fails
    assert r.returncode == 1
    assert json.loads(bp.read_text())["findings"] == \
        ["jaxpr-non-donated|jaxpr:t|y"]


def test_cli_selective_run_reports_no_phantom_stale(tmp_path):
    """An AST-only --select run must not call trace-rule baseline
    entries stale (the lint_prints.sh wrapper would otherwise nag on
    every invocation)."""
    _plant(tmp_path, "roc_tpu/clean.py", "x = 1\n")
    bp = tmp_path / "scripts" / "lint_baseline.json"
    bp.parent.mkdir()
    bp.write_text(json.dumps(
        {"version": 1, "findings": ["jaxpr-non-donated|jaxpr:t|y"]}))
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "roc_tpu.analysis",
         "--root", str(tmp_path), "--select", "stdout-print",
         "--strict"],
        capture_output=True, text=True, timeout=60, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 stale" in r.stdout
    assert "no longer fire" not in r.stdout
