"""roc-lint level six (analysis/concurrency_lint): every rule fires
on a synthetic violation tree, pragma suppression works, the REAL
tree audits clean with an empty findings baseline, the CLI gate (and
its `--select concurrency` alias) bites, and the discovered
concurrency surface documents the runtime's actual thread model."""

import json
import os
import subprocess
import sys

from roc_tpu.analysis.concurrency_lint import (
    CONCURRENCY_RULES, TreeModel, concurrency_surface,
    run_concurrency_lint)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _plant(root, relpath, text):
    p = root / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(text)


def _rules(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------- synthetic fixtures

def test_signal_unsafe_handler_fires(tmp_path):
    """A registered handler that emits/locks/imports/prints fires per
    violation; flag-only handlers and SIG_DFL stay quiet; the one-level
    call-graph walk catches a helper that emits."""
    _plant(tmp_path, "roc_tpu/sig.py",
           "import signal\n"
           "import threading\n"
           "from roc_tpu.obs.events import emit\n"
           "_LOCK = threading.Lock()\n"
           "FLAG = [False]\n"
           "def _helper():\n"
           "    emit('run', 'noooo')\n"                       # line 7
           "def bad_handler(signum, frame):\n"
           "    import os\n"                                  # line 9
           "    with _LOCK:\n"                                # line 10
           "        FLAG[0] = True\n"
           "    print('caught')\n"                            # line 12
           "    _helper()\n"
           "def good_handler(signum, frame):\n"
           "    FLAG[0] = True\n"
           "def install():\n"
           "    signal.signal(signal.SIGTERM, bad_handler)\n"
           "    signal.signal(signal.SIGINT, good_handler)\n"
           "    signal.signal(signal.SIGUSR1, signal.SIG_DFL)\n")
    got = run_concurrency_lint(str(tmp_path),
                               select=["signal-unsafe-handler"])
    lines = sorted(f.line for f in got)
    assert lines == [7, 9, 10, 12], \
        [(f.line, f.msg) for f in got]
    assert all(f.rule == "signal-unsafe-handler" for f in got)
    # the helper finding names both the handler and the via-path
    via = [f for f in got if f.line == 7]
    assert "via _helper" in via[0].msg


def test_lock_order_cycle_fires_and_pragma(tmp_path):
    """A seeded A->B / B->A nesting is a cycle; consistent nesting is
    not; a pragma on a participating acquisition suppresses it."""
    _plant(tmp_path, "roc_tpu/locks.py",
           "import threading\n"
           "A = threading.Lock()\n"
           "B = threading.Lock()\n"
           "def t1():\n"
           "    with A:\n"
           "        with B:\n"
           "            pass\n"
           "def t2():\n"
           "    with B:\n"
           "        with A:\n"
           "            pass\n")
    got = run_concurrency_lint(str(tmp_path),
                               select=["lock-order-cycle"])
    assert len(got) == 1
    assert got[0].rule == "lock-order-cycle"
    assert "A" in got[0].msg and "B" in got[0].msg
    # fingerprint is the sorted lock set — stable across line drift
    assert got[0].key.startswith("cycle=")

    # consistent ordering: no finding
    _plant(tmp_path, "roc_tpu/locks.py",
           "import threading\n"
           "A = threading.Lock()\n"
           "B = threading.Lock()\n"
           "def t1():\n"
           "    with A:\n"
           "        with B:\n"
           "            pass\n"
           "def t2():\n"
           "    with A:\n"
           "        with B:\n"
           "            pass\n")
    assert not run_concurrency_lint(str(tmp_path),
                                    select=["lock-order-cycle"])

    # pragma on one edge suppresses the cycle
    _plant(tmp_path, "roc_tpu/locks.py",
           "import threading\n"
           "A = threading.Lock()\n"
           "B = threading.Lock()\n"
           "def t1():\n"
           "    with A:\n"
           "        # B never contended: roc-lint: ok=lock-order-cycle\n"
           "        with B:\n"
           "            pass\n"
           "def t2():\n"
           "    with B:\n"
           "        with A:\n"
           "            pass\n")
    assert not run_concurrency_lint(str(tmp_path),
                                    select=["lock-order-cycle"])


def test_lock_order_cycle_through_call_chain(tmp_path):
    """The acquired-while-holding edge walks resolvable calls: a
    with-block calling a function that takes the other lock still
    closes the cycle."""
    _plant(tmp_path, "roc_tpu/locks2.py",
           "import threading\n"
           "A = threading.Lock()\n"
           "B = threading.Lock()\n"
           "def takes_b():\n"
           "    with B:\n"
           "        pass\n"
           "def t1():\n"
           "    with A:\n"
           "        takes_b()\n"
           "def t2():\n"
           "    with B:\n"
           "        with A:\n"
           "            pass\n")
    got = run_concurrency_lint(str(tmp_path),
                               select=["lock-order-cycle"])
    assert len(got) == 1


def test_condvar_wait_no_predicate_fires(tmp_path):
    """The seeded predicate-less Condition.wait() (the PR-11 race
    class) fires; while-loop waits and Event.wait stay quiet."""
    _plant(tmp_path, "roc_tpu/cv.py",
           "import threading\n"
           "class Q:\n"
           "    def __init__(self):\n"
           "        self._cv = threading.Condition()\n"
           "        self._stop = threading.Event()\n"
           "        self.items = []\n"
           "    def bad_take(self):\n"
           "        with self._cv:\n"
           "            if not self.items:\n"
           "                self._cv.wait()\n"               # line 10
           "            return self.items.pop()\n"
           "    def good_take(self):\n"
           "        with self._cv:\n"
           "            while not self.items:\n"
           "                self._cv.wait()\n"
           "            return self.items.pop()\n"
           "    def idle(self):\n"
           "        self._stop.wait(1.0)\n")    # Event: level-triggered
    got = run_concurrency_lint(str(tmp_path),
                               select=["condvar-wait-no-predicate"])
    assert [(f.rule, f.line) for f in got] == \
        [("condvar-wait-no-predicate", 10)]
    assert "Q.bad_take" in got[0].msg


def test_unguarded_shared_state_fires(tmp_path):
    """Attributes the thread body mutates (appends, augmented
    assigns) read from public methods without the lock fire; locked
    accesses, private methods, and constant flag publishes don't."""
    _plant(tmp_path, "roc_tpu/shared.py",
           "import threading\n"
           "class W:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self.vals = []\n"
           "        self.n = 0\n"
           "        self.done = False\n"
           "        self._t = threading.Thread(target=self._run)\n"
           "        self._t.start()\n"
           "    def _run(self):\n"
           "        while True:\n"
           "            with self._lock:\n"
           "                self.vals.append(1)\n"
           "            self.n += 1\n"
           "            self.done = True\n"      # flag publish: exempt
           "    def peek(self):\n"
           "        return list(self.vals), self.n\n"   # lines 17-18
           "    def peek_locked(self):\n"
           "        with self._lock:\n"
           "            return list(self.vals), self.n\n"
           "    def is_done(self):\n"
           "        return self.done\n"          # exempt flag
           "    def _private_peek(self):\n"
           "        return self.vals\n"
           "    def stop(self):\n"
           "        self._t.join()\n")
    got = run_concurrency_lint(str(tmp_path),
                               select=["unguarded-shared-state"])
    assert sorted(f.key for f in got) == ["W.peek:n", "W.peek:vals"]
    assert all("W.peek" in f.msg for f in got)


def test_blocking_under_lock_fires(tmp_path):
    """device_put / sleeps / file I/O / Future.result reachable while
    a lock is held fire (directly and one resolvable call deep);
    the same calls outside the lock, and pragma'd holds, stay quiet."""
    _plant(tmp_path, "roc_tpu/blk.py",
           "import threading\n"
           "import time\n"
           "import jax\n"
           "L = threading.Lock()\n"
           "def slow():\n"
           "    time.sleep(1.0)\n"
           "def f(x, fut):\n"
           "    with L:\n"
           "        y = jax.device_put(x)\n"                 # line 9
           "        time.sleep(0.1)\n"                       # line 10
           "        r = fut.result()\n"                      # line 11
           "        slow()\n"                                # line 12
           "    z = jax.device_put(x)\n"       # outside: fine
           "    time.sleep(0.1)\n"             # outside: fine
           "    return y, r, z\n"
           "def g(x):\n"
           "    with L:\n"
           "        # bounded: roc-lint: ok=blocking-under-lock\n"
           "        return jax.device_put(x)\n")
    got = run_concurrency_lint(str(tmp_path),
                               select=["blocking-under-lock"])
    assert sorted(f.line for f in got) == [9, 10, 11, 12]
    via = [f for f in got if f.line == 12]
    assert "via slow" in via[0].msg


def test_thread_no_shutdown_path_fires(tmp_path):
    """A thread nobody joins and whose body polls no stop Event fires
    (daemon= alone doesn't count); a joined thread and a
    stop-Event-polling thread are both fine."""
    _plant(tmp_path, "roc_tpu/thr.py",
           "import threading\n"
           "def _work():\n"
           "    while True:\n"
           "        pass\n"
           "def leak():\n"
           "    t = threading.Thread(target=_work, daemon=True)\n"
           "    t.start()\n"                                 # no join
           "def joined():\n"
           "    t = threading.Thread(target=_work)\n"
           "    t.start()\n"
           "    t.join()\n"
           "def evented():\n"
           "    stop = threading.Event()\n"
           "    def _poll():\n"
           "        while not stop.is_set():\n"
           "            pass\n"
           "    t = threading.Thread(target=_poll)\n"
           "    t.start()\n"
           "    stop.set()\n")
    got = run_concurrency_lint(str(tmp_path),
                               select=["thread-no-shutdown-path"])
    assert len(got) == 1
    assert got[0].line == 6
    assert "_work" in got[0].msg and "daemon" in got[0].msg


def test_lock_order_cycle_survives_mutual_recursion(tmp_path):
    """Regression (review): mutually recursive acquirers must not
    memo-poison the lock summary — the cycle cut returns a truncated
    set that, if cached as final, silently dropped the C->A edge and
    the genuine C->A->C deadlock with it."""
    _plant(tmp_path, "roc_tpu/rec.py",
           "import threading\n"
           "LA = threading.Lock()\n"
           "LB = threading.Lock()\n"
           "LC = threading.Lock()\n"
           "def a():\n"
           "    with LA:\n"
           "        pass\n"
           "    b()\n"
           "def b():\n"
           "    with LB:\n"
           "        pass\n"
           "    a()\n"                 # mutual recursion: cycle cut
           "def holder():\n"
           "    with LC:\n"
           "        b()\n"             # edges LC->LB AND LC->LA
           "def closer():\n"
           "    with LA:\n"
           "        with LC:\n"        # closes the LC->LA->LC cycle
           "            pass\n")
    got = run_concurrency_lint(str(tmp_path),
                               select=["lock-order-cycle"])
    assert len(got) == 1, [f.msg for f in got]
    assert "LC" in got[0].msg and "LA" in got[0].msg


def test_blocking_under_lock_thread_names_are_function_local(tmp_path):
    """Regression (review): a Thread stored to `t` in one function
    must not make an unrelated function's `t.join()` (a str/list
    join) a blocking finding."""
    _plant(tmp_path, "roc_tpu/blk2.py",
           "import threading\n"
           "L = threading.Lock()\n"
           "def spawns():\n"
           "    t = threading.Thread(target=print)\n"
           "    t.start()\n"
           "    t.join()\n"
           "def unrelated(parts):\n"
           "    t = ','\n"
           "    with L:\n"
           "        return t.join(parts)\n"    # str.join: not a thread
           "def real(pool):\n"
           "    t = threading.Thread(target=print)\n"
           "    t.start()\n"
           "    with L:\n"
           "        t.join()\n"                # line 15: genuine
           "    return t\n")
    got = run_concurrency_lint(str(tmp_path),
                               select=["blocking-under-lock"])
    assert [(f.line, f.rule) for f in got] == \
        [(15, "blocking-under-lock")], [(f.line, f.msg) for f in got]


def test_thread_shutdown_attr_joins_are_class_scoped(tmp_path):
    """Regression (review): ClassB joining its own `self._t` must not
    vouch for ClassA's never-joined, never-polling `self._t`."""
    _plant(tmp_path, "roc_tpu/thr2.py",
           "import threading\n"
           "class A:\n"
           "    def __init__(self):\n"
           "        self._t = threading.Thread(target=self._run)\n"
           "        self._t.start()\n"         # line 5: never joined
           "    def _run(self):\n"
           "        pass\n"
           "class B:\n"
           "    def __init__(self):\n"
           "        self._t = threading.Thread(target=self._run)\n"
           "        self._t.start()\n"
           "    def _run(self):\n"
           "        pass\n"
           "    def close(self):\n"
           "        self._t.join()\n")
    got = run_concurrency_lint(str(tmp_path),
                               select=["thread-no-shutdown-path"])
    assert len(got) == 1, [(f.line, f.msg) for f in got]
    assert got[0].line == 4


# ---------------------------------- explicit acquire()/release() pairs

def test_acquire_release_regions_model_held_locks(tmp_path):
    """ISSUE-13 satellite: explicit ``.acquire()``/``.release()``
    pairs model held regions exactly like with-blocks — the ordering
    graph closes cycles through them, blocking calls inside the span
    flag (including the ``acquire(); try: ... finally: release()``
    idiom), and statements AFTER the release are free."""
    _plant(tmp_path, "roc_tpu/acq.py",
           "import threading\n"
           "import time\n"
           "A = threading.Lock()\n"
           "B = threading.Lock()\n"
           "def t1():\n"
           "    A.acquire()\n"
           "    try:\n"
           "        time.sleep(1.0)\n"                         # line 8
           "        with B:\n"
           "            pass\n"
           "    finally:\n"
           "        A.release()\n"
           "def t2():\n"
           "    B.acquire()\n"
           "    with A:\n"                                     # line 15
           "        pass\n"
           "    B.release()\n"
           "def t3():\n"
           "    A.acquire()\n"
           "    time.sleep(0.5)\n"                             # line 20
           "    A.release()\n"
           "    time.sleep(0.5)\n")                            # line 22
    got = run_concurrency_lint(str(tmp_path))
    # A->B through t1's try/finally region, B->A through t2's span
    cyc = [f for f in got if f.rule == "lock-order-cycle"]
    assert len(cyc) == 1, [f.msg for f in got]
    assert "A" in cyc[0].msg and "B" in cyc[0].msg
    bl_lines = sorted(f.line for f in got
                      if f.rule == "blocking-under-lock")
    assert 8 in bl_lines       # sleep inside the try/finally region
    assert 20 in bl_lines      # sleep inside the plain span
    assert 22 not in bl_lines  # sleep AFTER the release is free


def test_acquire_without_release_holds_to_end(tmp_path):
    """A missing release is modeled as held-to-end-of-list — exactly
    what the leaked lock does at runtime."""
    _plant(tmp_path, "roc_tpu/leak.py",
           "import threading\n"
           "import time\n"
           "A = threading.Lock()\n"
           "def leaky():\n"
           "    A.acquire()\n"
           "    time.sleep(0.5)\n")                            # line 6
    got = run_concurrency_lint(str(tmp_path),
                               select=["blocking-under-lock"])
    assert [f.line for f in got] == [6], [f.msg for f in got]


def test_acquire_release_covers_unguarded_shared_state(tmp_path):
    """A public method reading thread-written state between
    ``acquire()`` and ``release()`` counts as guarded; the same read
    outside the span still fires — the Router/Server locking styles
    are both fully covered."""
    _plant(tmp_path, "roc_tpu/ug2.py",
           "import threading\n"
           "class W:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self.items = []\n"
           "        self._t = threading.Thread(target=self._run)\n"
           "        self._t.start()\n"
           "    def _run(self):\n"
           "        with self._lock:\n"
           "            self.items.append(1)\n"
           "    def good(self):\n"
           "        self._lock.acquire()\n"
           "        try:\n"
           "            return len(self.items)\n"
           "        finally:\n"
           "            self._lock.release()\n"
           "    def bad(self):\n"
           "        return len(self.items)\n"                  # line 18
           "    def close(self):\n"
           "        self._t.join()\n")
    got = run_concurrency_lint(str(tmp_path),
                               select=["unguarded-shared-state"])
    assert [f.line for f in got] == [18], \
        [(f.line, f.msg) for f in got]


# ----------------------------------- artifact lock ownership (ISSUE 14)

def test_artifact_lock_ownership_fires_on_ungated_writers(tmp_path):
    """Two writers to one rotation prefix without the shared-rotation
    handshake = one finding per write site; a process_index-gated
    writer and a per-process prefix are the sanctioned protocols."""
    _plant(tmp_path, "roc_tpu/ck.py",
           "from roc_tpu.resilience.recovery import "
           "CheckpointRotation\n"
           "def writer_a(tr):\n"
           "    rot = CheckpointRotation('shared/ck')\n"
           "    rot.save(tr)\n"                               # line 4
           "def writer_b(tr):\n"
           "    rot = CheckpointRotation('shared/ck')\n"
           "    rot.save(tr)\n"                               # line 7
           "def gated_writer(tr):\n"
           "    import jax\n"
           "    rot = CheckpointRotation('shared/ck')\n"
           "    if jax.process_index() == 0:\n"
           "        rot.save(tr)\n"
           "def per_proc_writer(tr):\n"
           "    import os\n"
           "    rot = CheckpointRotation(f'ck.{os.getpid()}')\n"
           "    rot.save(tr)\n")
    got = run_concurrency_lint(str(tmp_path),
                               select=["artifact-lock-ownership"])
    assert sorted(f.line for f in got) == [4, 7], \
        [(f.line, f.msg) for f in got]
    assert all(f.rule == "artifact-lock-ownership" for f in got)
    assert "shared-rotation handshake" in got[0].msg


def test_artifact_lock_ownership_bindings_are_function_scoped(
        tmp_path):
    """One function's per-process prefix must not vouch for another
    function's shared prefix just because both bind the name
    ``rot``."""
    _plant(tmp_path, "roc_tpu/ck.py",
           "from roc_tpu.resilience.recovery import "
           "CheckpointRotation\n"
           "import os\n"
           "def per_proc(tr):\n"
           "    rot = CheckpointRotation(f'ck.{os.getpid()}')\n"
           "    rot.save(tr)\n"
           "def shared(tr):\n"
           "    rot = CheckpointRotation('shared/ck')\n"
           "    rot.save(tr)\n")                              # line 8
    got = run_concurrency_lint(str(tmp_path),
                               select=["artifact-lock-ownership"])
    assert [f.line for f in got] == [8], \
        [(f.line, f.msg) for f in got]


def test_artifact_lock_ownership_local_binding_no_module_shadow(
        tmp_path):
    """A function-local per-process binding must not shadow the
    MODULE-level shared binding another function writes through."""
    _plant(tmp_path, "roc_tpu/ck.py",
           "import os\n"
           "from roc_tpu.resilience.recovery import "
           "CheckpointRotation\n"
           "rot = CheckpointRotation('shared/ck')\n"
           "def module_writer(tr):\n"
           "    rot.save(tr)\n"                               # line 5
           "def per_proc(tr):\n"
           "    rot = CheckpointRotation(f'ck.{os.getpid()}')\n"
           "    rot.save(tr)\n")
    got = run_concurrency_lint(str(tmp_path),
                               select=["artifact-lock-ownership"])
    assert [f.line for f in got] == [5], \
        [(f.line, f.msg) for f in got]
    assert "module_writer" in got[0].msg


def test_artifact_lock_ownership_attr_bindings_are_class_scoped(
        tmp_path):
    """Two classes reusing one attribute name: class A's per-process
    prefix must not exempt class B's shared-prefix writer."""
    _plant(tmp_path, "roc_tpu/ck.py",
           "import os\n"
           "from roc_tpu.resilience.recovery import "
           "CheckpointRotation\n"
           "class A:\n"
           "    def __init__(self):\n"
           "        self.rot = CheckpointRotation("
           "f'ck.{os.getpid()}')\n"
           "    def write(self, tr):\n"
           "        self.rot.save(tr)\n"
           "class B:\n"
           "    def __init__(self):\n"
           "        self.rot = CheckpointRotation('shared/ck')\n"
           "    def write(self, tr):\n"
           "        self.rot.save(tr)\n")                     # line 12
    got = run_concurrency_lint(str(tmp_path),
                               select=["artifact-lock-ownership"])
    assert [f.line for f in got] == [12], \
        [(f.line, f.msg) for f in got]
    assert "B.write" in got[0].msg


def test_artifact_lock_ownership_gate_via_callee_chain(tmp_path):
    """The real tree's shape: the write funnels through a helper that
    carries the gate (checkpoint_trainer's process_index() != 0
    return) — evidence travels the resolvable call chain, including
    through a tree-local CheckpointRotation.save."""
    _plant(tmp_path, "roc_tpu/ck.py",
           "import jax\n"
           "class CheckpointRotation:\n"
           "    def __init__(self, prefix):\n"
           "        self.prefix = prefix\n"
           "    def save(self, tr):\n"
           "        helper(tr, self.prefix)\n"
           "def helper(tr, p):\n"
           "    if jax.process_count() > 1 "
           "and jax.process_index() != 0:\n"
           "        return\n"
           "    open(p, 'w').close()\n"
           "def writer(tr):\n"
           "    rot = CheckpointRotation('shared/ck')\n"
           "    rot.save(tr)\n"
           "def direct(tr):\n"
           "    helper(tr, 'shared/ck')\n")
    got = run_concurrency_lint(str(tmp_path),
                               select=["artifact-lock-ownership"])
    assert got == [], [(f.line, f.msg) for f in got]


def test_artifact_lock_ownership_pragma_and_writer_fns(tmp_path):
    """Direct checkpoint_trainer()/save_checkpoint() call sites are
    writers too, and the standard pragma documents a known-single-
    writer site."""
    _plant(tmp_path, "roc_tpu/ck.py",
           "def checkpoint_trainer(tr, p):\n"
           "    pass\n"
           "def bad(tr):\n"
           "    checkpoint_trainer(tr, 'ck')\n"               # line 4
           "def vouched(tr):\n"
           "    # one bench child per stage: "
           "roc-lint: ok=artifact-lock-ownership\n"
           "    checkpoint_trainer(tr, 'ck')\n")
    got = run_concurrency_lint(str(tmp_path),
                               select=["artifact-lock-ownership"])
    assert [f.line for f in got] == [4], \
        [(f.line, f.msg) for f in got]


def test_artifact_surface_inventories_real_tree():
    """The surface documents which process-shared artifacts each
    module touches and their ownership protocol: the tree's rotation
    writers inherit the proc0 gate, the warm state publishes via
    atomic replace, the compile cache is multi-writer-safe."""
    surface = concurrency_surface(TreeModel(_REPO))
    arts = {m["module"]: m["artifacts"]
            for m in surface["artifacts"]}
    assert any(a["kind"] == "rotation"
               and a["owner"] == "proc0-gate"
               for a in arts.get("bench.py", [])), arts
    assert any(a["kind"] == "warm-state"
               and a["owner"] == "atomic-replace"
               for a in arts.get("roc_tpu/prewarm.py", []))
    assert any(a["kind"] == "compile-cache"
               for a in arts.get("roc_tpu/train/cli.py", []))
    # checkpoint-v3 writers (ISSUE 15): the per-shard writers (the
    # async saver thread's included) and the proc-0 manifest commit
    # are inventoried with their ownership protocol
    assert any(a["kind"] == "ckpt-manifest"
               and a["owner"] == "proc0-commit-after-shards"
               for a in arts.get("roc_tpu/utils/checkpoint.py", []))
    assert any(a["kind"] == "ckpt-shard"
               and a["owner"] == "per-process-file"
               for a in arts.get("roc_tpu/resilience/async_save.py",
                                 []))
    assert surface["totals"]["artifacts"] >= 5


# ------------------------------------------------- registration + tree

def test_rules_registered_and_not_trace():
    from roc_tpu.analysis.driver import all_rule_names, is_trace_rule
    names = all_rule_names()
    for r in CONCURRENCY_RULES:
        assert r in names
        # pure AST: a `--select concurrency` preflight must never
        # force the jax trace rig
        assert not is_trace_rule(r)


def test_tree_is_clean_and_baseline_empty():
    """The REAL tree audits clean (true positives were FIXED, not
    baselined): the findings baseline stays empty."""
    got = run_concurrency_lint(_REPO)
    assert got == [], "\n".join(f.render() for f in got)
    data = json.load(open(
        os.path.join(_REPO, "scripts", "lint_baseline.json")))
    assert data["findings"] == []


def test_surface_documents_the_runtime_thread_model():
    """The discovered surface names the threads/locks/handlers the
    runtime actually has — the audit doubling as documentation."""
    surface = concurrency_surface(TreeModel(_REPO))
    by_mod = {m["module"]: m for m in surface["modules"]}
    # the five known thread spawns
    assert "roc_tpu/core/streaming.py" in by_mod       # StagingPool
    assert "roc_tpu/serve/server.py" in by_mod         # Server._loop
    assert "roc_tpu/obs/heartbeat.py" in by_mod        # watchdog
    assert "bench.py" in by_mod                        # stderr reader
    # the checkpoint saver thread (ISSUE 15) — the tree-clean pin
    # above already proves all six rules model it
    asv = by_mod["roc_tpu/resilience/async_save.py"]
    assert any(t["target"] == "self._loop" for t in asv["threads"])
    assert any(lk["kind"] == "condition" for lk in asv["locks"])
    srv = by_mod["roc_tpu/serve/server.py"]
    assert any(t["target"] == "self._loop" for t in srv["threads"])
    assert any(lk["kind"] == "condition" for lk in srv["locks"])
    # the preemption guard's SIGTERM/SIGINT handler (SIG_DFL resets
    # are not handlers)
    pre = by_mod["roc_tpu/resilience/preempt.py"]
    assert any(h["handler"] == "_handle" for h in pre["handlers"])
    assert surface["totals"]["threads"] >= 4
    assert surface["totals"]["handlers"] >= 1


def test_report_renders_concurrency_surface_table():
    """roc_tpu.report renders the thread-model table from the
    --json payload (``--concurrency``) AND from the surface event an
    audited run leaves in its event stream."""
    import io

    from roc_tpu import report
    surface = concurrency_surface(TreeModel(_REPO))
    out = io.StringIO()
    report.summarize([], concurrency=surface, out=out)
    text = out.getvalue()
    assert "concurrency surface" in text
    assert "roc_tpu/serve/server.py" in text
    assert "Server._lock[condition]" in text
    # event-stream path: same table, no payload file needed
    ev = {"cat": "analysis", "kind": "concurrency_surface",
          "modules": surface["modules"], "totals": surface["totals"]}
    out2 = io.StringIO()
    report.summarize([ev], out=out2)
    assert "Server._lock[condition]" in out2.getvalue()


def test_known_pragmas_suppress_with_reasons():
    """The two sanctioned suppressions carry their why at the site:
    the preemption guard's async-signal-safe os.write and the event
    bus's serialized sink write."""
    src = open(os.path.join(
        _REPO, "roc_tpu", "resilience", "preempt.py")).read()
    assert "roc-lint: ok=signal-unsafe-handler" in src
    src = open(os.path.join(
        _REPO, "roc_tpu", "obs", "events.py")).read()
    assert "roc-lint: ok=blocking-under-lock" in src


# --------------------------------------------------------- CLI wiring

def _run_cli(args, cwd=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "roc_tpu.analysis"] + args,
        cwd=cwd or _REPO, capture_output=True, text=True, timeout=60,
        env=env)


def test_cli_select_concurrency_alias_green_on_tree():
    """`--select concurrency` (the test.sh / round6_chain preflight
    line) expands to all six rules, runs jax-free fast, and exits 0
    on the tree with the surface in the --json payload."""
    r = _run_cli(["--select", "concurrency", "--json"])
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["summary"]["new"] == 0
    surface = payload["concurrency_surface"]
    assert surface["totals"]["threads"] >= 4
    assert any(m["module"] == "roc_tpu/serve/server.py"
               for m in surface["modules"])


def test_cli_ratchet_bites_on_planted_violation(tmp_path):
    """A seeded predicate-less Condition.wait in a scratch tree fails
    the CLI through the alias (the ratchet bites from zero)."""
    _plant(tmp_path, "roc_tpu/srv.py",
           "import threading\n"
           "class S:\n"
           "    def __init__(self):\n"
           "        self._cv = threading.Condition()\n"
           "    def take(self):\n"
           "        with self._cv:\n"
           "            self._cv.wait()\n")
    r = _run_cli(["--root", str(tmp_path), "--select", "concurrency"])
    assert r.returncode == 1
    assert "condvar-wait-no-predicate" in r.stdout
    assert "srv.py" in r.stdout


def test_cli_never_absorbs_concurrency_findings(tmp_path):
    """--update-baseline must not absorb a live concurrency finding
    (shrink-only contract, same as every level)."""
    _plant(tmp_path, "roc_tpu/srv.py",
           "import threading\n"
           "class S:\n"
           "    def __init__(self):\n"
           "        self._cv = threading.Condition()\n"
           "    def take(self):\n"
           "        with self._cv:\n"
           "            self._cv.wait()\n")
    bp = tmp_path / "scripts" / "lint_baseline.json"
    bp.parent.mkdir()
    bp.write_text(json.dumps({"version": 1, "findings": []}))
    r = _run_cli(["--root", str(tmp_path), "--select", "concurrency",
                  "--update-baseline"])
    assert r.returncode == 1
    assert json.loads(bp.read_text())["findings"] == []
