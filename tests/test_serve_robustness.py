"""Serving robustness (ISSUE 13): deadlines, backpressure, versioned
tables, graceful drain, and the replicated-routing fault drills.

The contract under test — an ACCEPTED request completes with a correct
answer or fails with a typed ``serve/errors.py`` exception; never a
hang, never a wrong value:

- deadline'd requests resolve with ``ServeTimeout`` within ~one
  microbatch of their deadline; a saturating burst sheds typed
  ``ServeOverload`` at the bounded admission queue;
- a concurrent ``add_edges`` publish never tears a microbatch: every
  result is bit-exact for the table version it was served under
  (``ServeResult.version``), asserted under a client-thread stress —
  the versioned-swap acceptance criterion;
- ``drain()`` finishes in-flight work and rejects late submits with
  ``ServeClosed``;
- the Router drills run through the REAL export→cold-load→load-gen
  path with replica subprocesses: ``replica_sigkill`` mid-load fails
  over with zero lost/wrong answers and a timeline-visible failover
  marker, ``serve_io`` re-dispatches transparently,
  ``table_swap_mid_query`` finishes the in-flight batch on its
  captured version, ``replica_stall`` is bounded by hedging, and a
  SIGTERM'd replica drains gracefully (exit 0) — the PR-8 preemption
  contract applied to serving.
"""

import json
import os
import signal
import threading
import time

import numpy as np
import pytest

from roc_tpu.serve.errors import (ServeClosed, ServeOverload,
                                  ServeTimeout)

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _dataset(V=300, seed=0):
    from roc_tpu.core.graph import synthetic_dataset
    return synthetic_dataset(num_nodes=V, avg_degree=6, in_dim=24,
                             num_classes=5, seed=seed)


def _sgc_model():
    from roc_tpu.models.sgc import build_sgc
    return build_sgc([24, 5], k=2, dropout_rate=0.5)


def _config(**kw):
    from roc_tpu.train.trainer import TrainConfig
    kw.setdefault("verbose", False)
    kw.setdefault("symmetric", True)
    return TrainConfig(**kw)


@pytest.fixture(scope="module", autouse=True)
def _shed_native_jit_state():
    """The in-process rig + versioned-table stress compile several
    predictor/program variants into the pytest process; shed the
    accumulated native JIT state when the module ends (the PR-7/8
    mitigation for the known jaxlib-0.4.x XLA:CPU corruption flake
    under per-process compile churn — test_flat_sum /
    test_mixed_precision / test_drills carry the same fixture)."""
    yield
    import jax
    jax.clear_caches()


@pytest.fixture(scope="module")
def rig():
    """Predictor + full-table reference logits (fresh Glorot weights —
    robustness behavior is weight-independent)."""
    from roc_tpu.serve.export import build_predictor
    ds = _dataset()
    pred = build_predictor(_sgc_model(), ds, _config(),
                           backend="auto")
    ref = pred.query(np.arange(ds.graph.num_nodes))
    return ds, pred, ref


class _SlowPredictor:
    """Delegating wrapper whose dispatch sleeps — the knob that makes
    queue pressure deterministic on any CI box."""

    def __init__(self, pred, delay_s):
        self._pred = pred
        self.delay_s = delay_s

    def __getattr__(self, name):
        return getattr(self._pred, name)

    def query(self, ids, pub=None):
        time.sleep(self.delay_s)
        return self._pred.query(ids, pub=pub)


# --------------------------------------------- deadlines + backpressure

def test_deadline_returns_typed_timeout_within_budget(rig):
    """Queued requests whose deadline lapses while the dispatcher is
    busy resolve with ServeTimeout at the next microbatch boundary —
    never a hang, and never slower than ~deadline + one microbatch."""
    from roc_tpu.serve.server import Server
    ds, pred, ref = rig
    slow = _SlowPredictor(pred, 0.10)
    deadline_ms = 30.0
    with Server(slow, max_wait_ms=0.0, name="deadline") as srv:
        srv.submit([0])            # occupy the dispatcher ~100 ms
        # wait until that dispatch actually STARTED (otherwise the
        # deadline'd submits below would coalesce into the same first
        # microbatch and complete instead of queueing behind it)
        t_wait = time.monotonic()
        while not srv._dispatching and time.monotonic() - t_wait < 2.0:
            time.sleep(0.002)
        assert srv._dispatching
        futs = [(i, time.monotonic(),
                 srv.submit([i], deadline_ms=deadline_ms))
                for i in range(1, 9)]
        outcomes = []
        for i, t_sub, f in futs:
            try:
                rows = f.result(timeout=10)
                assert np.array_equal(rows, ref[[i]])
                outcomes.append(("ok", time.monotonic() - t_sub))
            except ServeTimeout:
                outcomes.append(("timeout", time.monotonic() - t_sub))
        stats = srv.stats()
    timeouts = [dt for kind, dt in outcomes if kind == "timeout"]
    assert timeouts, outcomes
    # budget: deadline + one microbatch (the 100 ms sleep) + sched
    # slack — generous for a loaded CI box, but a HANG (the 10 s
    # result timeout) can never pass
    budget_s = deadline_ms / 1e3 + slow.delay_s + 1.0
    assert max(timeouts) <= budget_s, outcomes
    assert stats["n_timeout"] == len(timeouts)
    assert stats["error_rate"] > 0


def test_saturating_burst_sheds_typed_overload(rig):
    """Past the bounded admission queue, submit() sheds immediately
    with ServeOverload; accepted requests still answer correctly and
    the shed rate shows in stats()."""
    from roc_tpu.serve.server import Server
    ds, pred, ref = rig
    slow = _SlowPredictor(pred, 0.05)
    with Server(slow, max_wait_ms=0.0, max_queue=4,
                name="overload") as srv:
        futs = [srv.submit([i % 50]) for i in range(60)]
        ok = shed = 0
        for i, f in enumerate(futs):
            try:
                rows = f.result(timeout=30)
                assert np.array_equal(rows, ref[[i % 50]])
                ok += 1
            except ServeOverload:
                shed += 1
        stats = srv.stats()
    assert ok + shed == 60
    assert shed > 0 and ok > 0
    assert stats["n_shed"] == shed
    # stats rounds rates to 4 decimals
    assert stats["shed_rate"] == pytest.approx(shed / 60, abs=1e-4)


# ------------------------------------------------------ versioned swap

def test_versioned_swap_concurrent_stress(rig):
    """THE versioned-table acceptance: client threads hammer the
    server while the control plane publishes two add_edges swaps.
    Every result must be bit-exact for the version stamped on it
    (``ServeResult.version``) — a torn batch (rows from two versions)
    or a value drifting from its version's table is a failure."""
    from roc_tpu.serve.export import build_predictor
    from roc_tpu.serve.server import Server
    ds = _dataset(seed=3)
    pred = build_predictor(_sgc_model(), ds, _config(),
                           backend="auto")
    probe = np.arange(0, ds.graph.num_nodes, 3, dtype=np.int32)
    pubs = {0: pred.published()}
    expected = {0: pred.query(probe, pub=pubs[0])}
    results = []
    errors = []
    stop = threading.Event()

    def client(seed):
        rng = np.random.default_rng(seed)
        try:
            while not stop.is_set():
                k = int(rng.integers(1, 12))
                idx = rng.integers(0, probe.size, size=k)
                rows = srv.submit(probe[idx]).result(timeout=30)
                results.append((int(rows.version), idx,
                                np.asarray(rows)))
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    with Server(pred, max_wait_ms=1.0, name="swap") as srv:
        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(4)]
        for t in threads:
            t.start()
        # two real mutations mid-stream; snapshot each published
        # version's expected values THROUGH the pinned-pub query path
        for u, v in ((1, 200), (7, 150)):
            time.sleep(0.15)
            pred.invalidate([u, v], [v, u])
            pub = pred.published()
            pubs[pub.version] = pub
            expected[pub.version] = pred.query(probe, pub=pub)
        time.sleep(0.15)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        assert not any(t.is_alive() for t in threads)
    assert not errors, errors[:3]
    assert len(results) > 20
    versions_seen = {v for v, _, _ in results}
    assert versions_seen >= {0, 2}, versions_seen
    for version, idx, rows in results:
        assert version in expected, version
        want = expected[version][idx]
        assert np.array_equal(rows, want), (
            f"version {version} result not bit-exact for its table")


def test_old_version_buffer_survives_publish(rig):
    """The copy-on-write publish: a pinned pre-swap TableVersion
    still answers bit-exact after two further publishes."""
    from roc_tpu.serve.export import build_predictor
    ds = _dataset(seed=5)
    pred = build_predictor(_sgc_model(), ds, _config(),
                           backend="auto")
    probe = np.arange(ds.graph.num_nodes)
    pub0 = pred.published()
    before = pred.query(probe, pub=pub0)
    pred.invalidate([2, 100], [100, 2])
    pred.invalidate([9, 50], [50, 9])
    assert pred.published().version == 2
    again = pred.query(probe, pub=pub0)
    assert np.array_equal(before, again)
    assert not np.array_equal(before, pred.query(probe))


# ------------------------------------------------------------- drain

def test_drain_finishes_inflight_then_rejects(rig):
    """drain(): accepted requests complete (correct answers), late
    submits fail typed ServeClosed, dispatcher thread gone."""
    from roc_tpu.serve.server import Server
    ds, pred, ref = rig
    slow = _SlowPredictor(pred, 0.03)
    srv = Server(slow, max_wait_ms=0.0, name="drain")
    futs = [srv.submit([i]) for i in range(8)]
    assert srv.drain(timeout=30)
    for i, f in enumerate(futs):
        assert np.array_equal(f.result(timeout=1), ref[[i]])
    with pytest.raises(ServeClosed):
        srv.submit([0]).result()
    assert not srv._thread.is_alive()


# ----------------------------------------------- fault-injection sites

def test_serve_fault_sites_parse_and_gate():
    """The serve sites ride the standard site:epoch[:proc] grammar,
    and note_proc_index pins the replica identity the :proc arm
    matches against."""
    from roc_tpu.resilience import inject
    try:
        spec = inject.parse("replica_sigkill:3:1")
        assert (spec.site, spec.epoch, spec.proc) == \
            ("replica_sigkill", 3, 1)
        for site in ("replica_stall", "table_swap_mid_query",
                     "serve_io"):
            assert inject.parse(f"{site}:0").site == site
        inject.disarm()
        inject.arm("serve_io:0:1")
        inject.note_proc_index(0)

        class _Srv:     # never touched: wrong proc
            pass
        inject.serve_batch_hooks(_Srv(), 5)   # no raise — proc gate
        inject.note_proc_index(1)
        with pytest.raises(OSError, match="injected serve I/O"):
            inject.serve_batch_hooks(_Srv(), 5)
        # fired once: spent
        inject.serve_batch_hooks(_Srv(), 6)
    finally:
        inject.disarm()


# --------------------------------------------------- router drills (e2e)

@pytest.fixture(scope="module")
def artifact(tmp_path_factory):
    """One exported artifact + warm persistent cache shared by every
    router drill: replicas cold-load with zero new compiles, so each
    subprocess costs import time, not compile time."""
    from roc_tpu.serve.export import build_predictor, export_predictor
    d = tmp_path_factory.mktemp("serve_art")
    cache = str(d / "cache")
    os.makedirs(cache)
    os.environ["ROC_TPU_CACHE_DIR"] = cache
    os.environ["ROC_TPU_CACHE_MIN_SECS"] = "0"
    ds = _dataset()
    pred = build_predictor(_sgc_model(), ds, _config(),
                           backend="precomputed")
    art = str(d / "artifact")
    export_predictor(pred, art,
                     dataset_meta={"V": ds.graph.num_nodes,
                                   "E": int(ds.graph.num_edges)})
    ref = pred.query(np.arange(ds.graph.num_nodes))
    yield art, ref, ds
    os.environ.pop("ROC_TPU_CACHE_DIR", None)


def _router_env(fault=None):
    env = os.environ.copy()
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("ROC_TPU_FAULT", None)
    if fault:
        env["ROC_TPU_FAULT"] = fault
    return env


def test_router_failover_replica_sigkill(artifact, tmp_path):
    """THE failover acceptance drill: SIGKILL one of 2 replicas
    mid-load — every accepted request completes with a correct answer
    or a typed deadline failure (zero hangs, zero wrong values), and
    the failover is a timeline-renderable marker."""
    from roc_tpu.obs.events import configure
    from roc_tpu.obs.timeline import merge_timeline
    from roc_tpu.serve.router import Router
    art, ref, ds = artifact
    ev_path = str(tmp_path / "ev.jsonl")
    configure(jsonl_path=ev_path)
    try:
        with Router(art, n_replicas=2, cpu=True,
                    env=_router_env("replica_sigkill:2:1"),
                    default_deadline_ms=20_000.0,
                    replica_args=["--drain-timeout", "3"]) as router:
            # Warm the query path on BOTH replicas before the burst: a
            # replica still wedged in its first dispatch never reaches
            # the armed microbatch index — every hedge quietly lands on
            # the other replica and the kill site never fires.  Probes
            # go in pairs (least-loaded dispatch breaks an idle tie
            # toward replica 0, so singles warm only one side); a
            # replica the site already killed counts as warmed-enough.
            t_warm = time.monotonic() + 120.0
            while time.monotonic() < t_warm:
                for p in [router.submit([0, 1]) for _ in range(2)]:
                    p.result(timeout=60)
                reps = router.stats()["replicas"]
                if (any(not r["alive"] for r in reps)
                        or all(r["served"] > 0 for r in reps)):
                    break
                time.sleep(0.05)
            futs = []
            for i in range(60):
                futs.append((i, router.submit([i % ds.graph.num_nodes,
                                               (i * 3) % 200])))
                time.sleep(0.002)
            ok = timeouts = 0
            for idx, fut in futs:
                try:
                    rows = fut.result(timeout=60)   # bounded: no hangs
                    want = ref[[idx % ds.graph.num_nodes,
                                (idx * 3) % 200]]
                    assert np.abs(np.asarray(rows) - want).max() \
                        <= 1e-5, idx
                    ok += 1
                except ServeTimeout:
                    timeouts += 1
            stats = router.stats()
        assert ok + timeouts == 60
        assert ok > 0
        alive = [r for r in stats["replicas"] if r["alive"]]
        assert len(alive) == 1, stats["replicas"]
    finally:
        configure(jsonl_path=None)
    events = [json.loads(l) for l in open(ev_path) if l.strip()]
    fo = [e for e in events if e.get("cat") == "serve"
          and e.get("kind") == "failover"]
    assert fo and fo[0].get("replica") == 1
    # the marker renders on the merged timeline
    doc = merge_timeline(events)
    names = {t.get("name") for t in doc["traceEvents"]}
    assert "serve:failover" in names, sorted(names)[:20]


def test_router_serve_io_redispatches(artifact):
    """A retryable replica-side failure (the serve_io drill) is
    re-dispatched transparently — the client still gets the right
    answer, and the redispatch leaves a dated serve event."""
    from roc_tpu.serve.router import Router
    art, ref, ds = artifact
    with Router(art, n_replicas=2, cpu=True,
                env=_router_env("serve_io:1:0"),
                default_deadline_ms=30_000.0,
                replica_args=["--drain-timeout", "3"]) as router:
        futs = [router.submit([i]) for i in range(30)]
        for i, f in enumerate(futs):
            rows = f.result(timeout=60)
            assert np.abs(np.asarray(rows) - ref[[i]]).max() <= 1e-5
        stats = router.stats()
    assert stats["n_ok"] == 30
    assert stats["n_failed"] == 0


def test_router_table_swap_mid_query_drill(artifact):
    """table_swap_mid_query: replica 0 publishes a REAL add_edges
    version swap between a microbatch's version capture and its
    dispatch.  Every answer must match either the pre-swap or the
    post-swap table — a torn batch matches neither."""
    from roc_tpu.serve.export import load_predictor
    from roc_tpu.serve.router import Router
    art, ref, ds = artifact
    # post-swap reference: replay the drill's mutation (self edge on
    # node 0) on a fresh artifact load
    pred2 = load_predictor(art)
    pred2.invalidate([0], [0])
    ref_new = pred2.query(np.arange(ds.graph.num_nodes))
    probe = np.arange(0, 200, dtype=np.int32)
    with Router(art, n_replicas=2, cpu=True,
                env=_router_env("table_swap_mid_query:1:0"),
                default_deadline_ms=30_000.0,
                replica_args=["--drain-timeout", "3"]) as router:
        futs = [router.submit([int(i)]) for i in probe]
        for i, f in enumerate(futs):
            rows = np.asarray(f.result(timeout=60))
            old_ok = np.abs(rows - ref[[i]]).max() <= 1e-5
            new_ok = np.abs(rows - ref_new[[i]]).max() <= 1e-5
            assert old_ok or new_ok, (
                f"row {i} matches NEITHER table version — torn batch")
        stats = router.stats()
    assert stats["n_ok"] == probe.size


@pytest.mark.slow
def test_router_hedges_stalled_replica(artifact):
    """replica_stall: one replica wedges a dispatch forever; hedged
    re-dispatch (latency-percentile trigger) answers from the healthy
    replica — stragglers cost a hedge, not a hung client."""
    from roc_tpu.serve.router import Router
    art, ref, ds = artifact
    with Router(art, n_replicas=2, cpu=True,
                env=_router_env("replica_stall:2:0"),
                default_deadline_ms=30_000.0,
                hedge_min_ms=150.0,
                replica_args=["--drain-timeout", "2"]) as router:
        futs = []
        for i in range(40):
            futs.append((i, router.submit([i])))
            time.sleep(0.003)
        ok = timeouts = 0
        for i, fut in futs:
            try:
                rows = fut.result(timeout=60)
                assert np.abs(np.asarray(rows) - ref[[i]]).max() \
                    <= 1e-5
                ok += 1
            except ServeTimeout:
                timeouts += 1
        stats = router.stats()
    assert ok + timeouts == 40 and ok > 0
    assert stats["n_hedge"] >= 1, stats


def test_replica_drains_gracefully_on_sigterm(artifact):
    """The PR-8 preemption contract on the serving tier: SIGTERM to a
    replica → it stops admitting, finishes in-flight, writes the
    drained line, exits 0 — and the router fails over around it."""
    from roc_tpu.serve.router import Router
    art, ref, ds = artifact
    with Router(art, n_replicas=2, cpu=True, env=_router_env(),
                default_deadline_ms=20_000.0,
                replica_args=["--drain-timeout", "5"]) as router:
        for i in range(10):
            rows = router.submit([i]).result(timeout=60)
            assert np.abs(np.asarray(rows) - ref[[i]]).max() <= 1e-5
        victim = router.replicas[0].proc
        victim.send_signal(signal.SIGTERM)
        rc = victim.wait(timeout=30)
        assert rc == 0, "drain must exit 0, not crash"
        # the survivor keeps serving
        for i in range(10, 20):
            rows = router.submit([i]).result(timeout=60)
            assert np.abs(np.asarray(rows) - ref[[i]]).max() <= 1e-5
        stats = router.stats()
    assert sum(1 for r in stats["replicas"] if r["alive"]) == 1
