"""CLI surface (roc_tpu/train/cli.py): flag plumbing, validation, and
the train/eval/checkpoint entry points, in-process on CPU."""

import numpy as np
import pytest

from roc_tpu.train import cli


def _run(argv):
    return cli.main(["--cpu", "--no-compile-cache"] + argv)


def test_synthetic_train_succeeds(capsys):
    rc = _run(["-e", "3", "-layers", "8-8-3", "--eval-every", "3",
               "--impl", "ell"])
    assert rc == 0
    assert "[INFER]" in capsys.readouterr().out


def test_checkpoint_resume_eval_only(tmp_path, capsys):
    ck = str(tmp_path / "ck.npz")
    assert _run(["-e", "3", "-layers", "8-8-3", "--impl", "ell",
                 "--checkpoint", ck]) == 0
    capsys.readouterr()
    rc = _run(["-e", "3", "-layers", "8-8-3", "--impl", "ell",
               "--resume", ck, "--eval-only"])
    assert rc == 0
    out = capsys.readouterr().out
    # one INFER line at the restored epoch, no training
    assert out.count("[INFER]") == 1
    assert "[INFER][3]" in out


@pytest.mark.parametrize("argv,msg", [
    (["-layers", "8"], "at least"),
    (["--model", "gcn", "--heads", "4", "-layers", "8-8-3"],
     "--heads applies"),
    (["--model", "gat", "--heads", "0", "-layers", "8-8-3"],
     ">= 1"),
    (["--model", "gat", "--heads", "3", "-layers", "8-8-3"],
     "divisible"),
    (["--halo", "ring", "-layers", "8-8-3"], "--parts"),
])
def test_flag_validation_fails_fast(argv, msg, capsys):
    assert _run(argv) == 2
    assert msg in capsys.readouterr().err


def test_gat_mixed_distributed(capsys):
    rc = _run(["-e", "2", "-layers", "8-8-3", "--model", "gat",
               "--heads", "2", "--dtype", "mixed", "--parts", "2",
               "--eval-every", "2"])
    assert rc == 0
    assert "[INFER]" in capsys.readouterr().out
