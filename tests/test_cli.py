"""CLI surface (roc_tpu/train/cli.py): flag plumbing, validation, and
the train/eval/checkpoint entry points, in-process on CPU."""

import numpy as np
import pytest

from roc_tpu.train import cli


def _run(argv):
    return cli.main(["--cpu", "--no-compile-cache"] + argv)


def test_synthetic_train_succeeds(capsys):
    rc = _run(["-e", "3", "-layers", "8-8-3", "--eval-every", "3",
               "--impl", "ell"])
    assert rc == 0
    assert "[INFER]" in capsys.readouterr().out


def test_checkpoint_resume_eval_only(tmp_path, capsys):
    ck = str(tmp_path / "ck.npz")
    assert _run(["-e", "3", "-layers", "8-8-3", "--impl", "ell",
                 "--checkpoint", ck]) == 0
    capsys.readouterr()
    rc = _run(["-e", "3", "-layers", "8-8-3", "--impl", "ell",
               "--resume", ck, "--eval-only"])
    assert rc == 0
    out = capsys.readouterr().out
    # one INFER line at the restored epoch, no training
    assert out.count("[INFER]") == 1
    assert "[INFER][3]" in out


@pytest.mark.parametrize("argv,msg", [
    (["-layers", "8"], "at least"),
    (["--model", "gcn", "--heads", "4", "-layers", "8-8-3"],
     "--heads applies"),
    (["--model", "gat", "--heads", "0", "-layers", "8-8-3"],
     ">= 1"),
    (["--model", "gat", "--heads", "3", "-layers", "8-8-3"],
     "divisible"),
    (["--halo", "ring", "-layers", "8-8-3"], "--parts"),
    (["--model", "gcn", "--learn-eps", "-layers", "8-8-3"],
     "--learn-eps applies"),
])
def test_flag_validation_fails_fast(argv, msg, capsys):
    assert _run(argv) == 2
    assert msg in capsys.readouterr().err


def test_save_logits_matches_metrics(tmp_path, capsys):
    """--save-logits writes [V, C] fp32 whose argmax reproduces the
    printed test accuracy — i.e. the export really is the final
    model's inference output."""
    import re
    path = str(tmp_path / "lg.npy")
    rc = _run(["-e", "3", "-layers", "8-8-3", "--impl", "ell",
               "--eval-every", "3", "--save-logits", path])
    assert rc == 0
    out = capsys.readouterr().out
    printed = re.findall(r"test_accuracy: [\d.]+%\((\d+)/(\d+)\)", out)
    assert printed, out
    correct, cnt = map(int, printed[-1])
    logits = np.load(path)
    assert logits.shape[1] == 3 and logits.dtype == np.float32
    # recompute test accuracy from the exported logits
    from roc_tpu.core.graph import MASK_TEST, synthetic_dataset
    ds = synthetic_dataset(512, 8, in_dim=8, num_classes=3, seed=1)
    sel = ds.mask == MASK_TEST
    got_correct = int((np.argmax(logits[sel], axis=1)
                       == ds.labels[sel]).sum())
    assert (got_correct, int(sel.sum())) == (correct, cnt)


def test_save_logits_reorder_inverts_to_original_order(tmp_path):
    """The same (seeded) run with and without --reorder bfs must save
    logits for the same vertices in the same ORIGINAL order — the
    permutation round-trips."""
    outs = {}
    for tag, extra in (("plain", []), ("bfs", ["--reorder", "bfs"])):
        path = str(tmp_path / f"{tag}.npy")
        rc = _run(["-e", "4", "-layers", "8-8-3", "--impl", "ell",
                   "-dropout", "0.0", "--eval-every", "1000",
                   "--save-logits", path] + extra)
        assert rc == 0
        outs[tag] = np.load(path)
    # identical params (graph-independent init) + relabeling-invariant
    # math => logits match vertex-for-vertex up to fp association
    np.testing.assert_allclose(outs["plain"], outs["bfs"],
                               rtol=2e-3, atol=2e-4)


def test_distributed_predict_matches_single():
    """DistributedTrainer.predict returns original-order logits equal
    to the single-device forward for the same params."""
    import jax
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig, Trainer
    ds = synthetic_dataset(128, 6, in_dim=8, num_classes=3, seed=4)
    model = build_gcn([8, 8, 3], dropout_rate=0.0)
    cfg = TrainConfig(aggr_impl="ell", verbose=False, chunk=64,
                      eval_every=1 << 30)
    dt = DistributedTrainer(model, ds, 4, cfg)
    tr = Trainer(model, ds, cfg)
    tr.params = jax.device_get(dt.params)
    np.testing.assert_allclose(np.asarray(dt.predict()),
                               np.asarray(tr.predict()),
                               rtol=1e-4, atol=1e-5)


def test_gin_learn_eps_cli(capsys):
    rc = _run(["-e", "2", "-layers", "8-8-3", "--model", "gin",
               "--learn-eps", "--impl", "ell", "--eval-every", "2"])
    assert rc == 0
    assert "[INFER]" in capsys.readouterr().out


def test_gat_mixed_distributed(capsys):
    rc = _run(["-e", "2", "-layers", "8-8-3", "--model", "gat",
               "--heads", "2", "--dtype", "mixed", "--parts", "2",
               "--eval-every", "2"])
    assert rc == 0
    assert "[INFER]" in capsys.readouterr().out


def test_cli_sgc_model_trains():
    """--model sgc --hops: the SGC family end-to-end through the CLI."""
    rc = _run(["--model", "sgc", "--hops", "2", "-layers", "12-4",
               "-e", "3", "-lr", "0.2"])
    assert rc == 0


def test_cli_appnp_model_trains_and_validates():
    """--model appnp end-to-end, and --alpha misuse fails fast (before
    any dataset load): on a non-appnp model, and out of [0, 1]."""
    rc = _run(["--model", "appnp", "--hops", "3", "--alpha", "0.2",
               "-layers", "12-8-4", "-e", "3", "-lr", "0.05"])
    assert rc == 0
    assert _run(["--model", "gcn", "--alpha", "0.3",
                 "-layers", "12-4", "-e", "1"]) == 2
    # the default VALUE passed explicitly is still misuse (sentinel)
    assert _run(["--model", "gcn", "--alpha", "0.1",
                 "-layers", "12-4", "-e", "1"]) == 2
    # --hops rides the same sentinel policy
    assert _run(["--model", "gcn", "--hops", "2",
                 "-layers", "12-4", "-e", "1"]) == 2
    assert _run(["--model", "appnp", "--hops", "0",
                 "-layers", "12-4", "-e", "1"]) == 2
    assert _run(["--model", "appnp", "--alpha", "1.5",
                 "-layers", "12-4", "-e", "1"]) == 2


def test_cli_gcn2_model_trains_and_validates():
    """--model gcn2 end-to-end (deep stack), and --lam / hidden-width
    / depth misuse fails fast (exit 2, before any dataset load)."""
    rc = _run(["--model", "gcn2", "-layers", "12-16-16-16-4",
               "-e", "3", "-lr", "0.05"])
    assert rc == 0
    assert _run(["--model", "gcn", "--lam", "0.5",
                 "-layers", "12-4", "-e", "1"]) == 2
    assert _run(["--model", "gcn2", "--lam", "0",
                 "-layers", "12-16-4", "-e", "1"]) == 2
    # structural -layers misuse: mismatched widths / no hidden layer
    assert _run(["--model", "gcn2", "-layers", "12-16-24-4",
                 "-e", "1"]) == 2
    assert _run(["--model", "gcn2", "-layers", "12-4", "-e", "1"]) == 2
