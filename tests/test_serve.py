"""Serving tier correctness (ISSUE 11, ``roc_tpu/serve``):

- serve-vs-train parity: served logits == ``Trainer.predict()`` to
  1e-5 for GCN (full-graph backend) and SGC (precomputed-propagation
  backend), including a server restored from a training checkpoint
  through the export CLI;
- microbatch coalescing bit-exactness vs one-at-a-time submission;
- THE acceptance criterion: a cold server process started from an
  exported artifact answers its first query with ZERO new compiled
  programs (program-key parity vs the export-time warm state, no new
  serve entries in the persistent cache);
- incremental ``S^k X`` recompute parity vs a full rebuild after an
  edge append;
- ``predict(node_ids=)`` row-subset gather on both trainers;
- the programspace/prewarm integration of the ``sgc_serve`` rig.
"""

import json
import os
import re
import subprocess
import sys

import jax
import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_WORKER = os.path.join(_REPO, "tests", "serve_worker.py")

# persistent-cache entries of SERVE programs (Predictor._serve_step)
_SERVE_ENTRY = re.compile(r"jit__serve_step")


def _dataset(V=300, seed=0):
    from roc_tpu.core.graph import synthetic_dataset
    return synthetic_dataset(num_nodes=V, avg_degree=6, in_dim=24,
                             num_classes=5, seed=seed)


def _sgc_model():
    from roc_tpu.models.sgc import build_sgc
    return build_sgc([24, 5], k=2, dropout_rate=0.5)


def _gcn_model():
    from roc_tpu.models.gcn import build_gcn
    return build_gcn([24, 16, 5], dropout_rate=0.5)


def _config(**kw):
    from roc_tpu.train.trainer import TrainConfig
    kw.setdefault("verbose", False)
    kw.setdefault("symmetric", True)
    return TrainConfig(**kw)


@pytest.fixture(scope="module")
def sgc_rig():
    from roc_tpu.train.trainer import Trainer
    ds = _dataset()
    tr = Trainer(_sgc_model(), ds, _config())
    tr.train(2)
    return ds, tr, np.asarray(jax.device_get(tr.predict()))


@pytest.fixture(scope="module")
def gcn_rig():
    from roc_tpu.train.trainer import Trainer
    ds = _dataset()
    tr = Trainer(_gcn_model(), ds, _config())
    tr.train(2)
    return ds, tr, np.asarray(jax.device_get(tr.predict()))


# ------------------------------------------------------------- parity

def test_precomputed_backend_parity_sgc(sgc_rig):
    """SGC through the precomputed-propagation backend: gather + dense
    head equals the trainer's full eval program."""
    from roc_tpu.serve.export import build_predictor
    ds, tr, ref = sgc_rig
    pred = build_predictor(_sgc_model(), ds, _config(),
                           params=tr.params, backend="auto")
    assert pred.backend == "precomputed" and pred.flavor == "akx"
    out = pred.query(np.arange(ds.graph.num_nodes))
    assert np.abs(out - ref).max() <= 1e-5
    # odd-sized subsets hit the padded buckets
    sub = pred.query([7, 123, 250])
    assert np.abs(sub - ref[[7, 123, 250]]).max() <= 1e-5


def test_full_backend_parity_gcn(gcn_rig):
    """GCN (no fixed propagation) through the full-graph backend."""
    from roc_tpu.serve.export import build_predictor
    ds, tr, ref = gcn_rig
    pred = build_predictor(tr.model, ds, tr.config,
                           params=tr.params, backend="auto")
    assert pred.backend == "full"
    out = pred.query(np.arange(ds.graph.num_nodes))
    assert np.abs(out - ref).max() <= 1e-5


def test_table_flavor_parity_appnp():
    """APPNP (propagation AFTER the MLP) under backend='precomputed'
    serves the frozen full-forward logits — the gather-only flavor."""
    from roc_tpu.models.appnp import build_appnp
    from roc_tpu.serve.export import build_predictor
    from roc_tpu.train.trainer import Trainer
    ds = _dataset()
    tr = Trainer(build_appnp([24, 8, 5], k=3, dropout_rate=0.5),
                 ds, _config())
    tr.train(1)
    ref = np.asarray(jax.device_get(tr.predict()))
    pred = build_predictor(tr.model, ds, tr.config, params=tr.params,
                           backend="precomputed")
    assert pred.flavor == "table"
    out = pred.query(np.arange(ds.graph.num_nodes))
    assert np.abs(out - ref).max() <= 1e-5


def test_restored_checkpoint_server_parity(sgc_rig, tmp_path):
    """Checkpoint → export CLI → artifact → Predictor equals the live
    trainer's predictions (the deploy path end to end), and the
    restore never constructs a trainer (restore_params_only)."""
    from roc_tpu.serve.export import load_predictor, main as export_main
    from roc_tpu.utils.checkpoint import checkpoint_trainer
    ds, tr, ref = sgc_rig
    ck = str(tmp_path / "sgc.npz")
    checkpoint_trainer(tr, ck)
    art = str(tmp_path / "artifact")
    # the CLI's synthetic dataset must BE the rig dataset: same
    # builder, same seed (seed=0 here; -seed also seeds the dataset)
    rc = export_main(["--checkpoint", ck, "--out", art,
                      "--model", "sgc", "-layers", "24-5", "--hops",
                      "2", "-seed", "0", "--cpu"])
    assert rc == 0
    # the synthetic dataset the CLI builds is 512 nodes with seed=0 —
    # not the rig's 300 — so compare through a predictor rebuilt on
    # the rig dataset instead: restore params only, build, compare
    from roc_tpu.serve.export import build_predictor
    from roc_tpu.utils.checkpoint import restore_params_only
    params, fp, epoch = restore_params_only(ck)
    assert fp.get("strict", {}).get("params_sig")
    assert epoch == tr.epoch
    pred = build_predictor(_sgc_model(), ds, _config(), params=params,
                           backend="auto")
    out = pred.query(np.arange(ds.graph.num_nodes))
    assert np.abs(out - ref).max() <= 1e-5


def test_export_load_roundtrip_parity(sgc_rig, tmp_path):
    """export_trainer → load_predictor: the artifact round trip is
    exact, and the manifest's program keys equal the loaded
    predictor's."""
    from roc_tpu.serve.export import export_trainer, load_predictor
    ds, tr, ref = sgc_rig
    art = str(tmp_path / "art")
    man = export_trainer(tr, ds, art)
    pred = load_predictor(art)
    out = pred.query(np.arange(ds.graph.num_nodes))
    assert np.abs(out - ref).max() <= 1e-5
    assert sorted(man["program_keys"]) == pred.program_keys()
    assert man["prewarm"]["verified_warm_hits"] == \
        man["prewarm"]["programs"]


# ------------------------------------------------- predict(node_ids=)

def test_trainer_predict_node_ids(gcn_rig):
    ds, tr, ref = gcn_rig
    rows = np.asarray(jax.device_get(
        tr.predict(node_ids=[5, 0, 299, 123])))
    assert rows.shape == (4, 5)
    assert np.array_equal(rows, ref[[5, 0, 299, 123]])


def test_distributed_predict_node_ids():
    from roc_tpu.parallel.distributed import DistributedTrainer
    ds = _dataset()
    tr = DistributedTrainer(_gcn_model(), ds, 2, _config())
    tr.train(1)
    full = tr.predict()
    rows = tr.predict(node_ids=[0, 131, 299, 7])
    assert rows.shape == (4, 5)
    assert np.array_equal(rows, full[[0, 131, 299, 7]])


# ------------------------------------------------------- microbatching

def test_microbatch_coalescing_bit_exact(sgc_rig):
    """Coalesced dispatch is BIT-identical to one-at-a-time
    submission: each served row is an independent dot-product chain,
    so batch composition cannot change it."""
    from roc_tpu.serve.export import build_predictor
    from roc_tpu.serve.server import Server
    ds, tr, _ = sgc_rig
    pred = build_predictor(_sgc_model(), ds, _config(),
                           params=tr.params, backend="auto")
    ids = [3, 99, 250, 17, 0, 299]
    solo = np.concatenate([pred.query([i]) for i in ids])
    with Server(pred, max_wait_ms=20.0) as srv:
        futs = [srv.submit([i]) for i in ids]
        got = np.concatenate([f.result() for f in futs])
        stats = srv.stats()
    assert np.array_equal(solo, got)
    # the burst actually coalesced (20 ms linger, submissions µs apart)
    assert stats["n_batches"] < stats["n_queries"]


@pytest.mark.slow
def test_server_multithreaded_submit_stress(sgc_rig):
    """The dynamic witness for roc-lint level six's static rules
    (tests/test_concurrency_lint.py): N client threads x M queries
    hammering one Server concurrently — every result bit-exact vs
    solo submission (no cross-request row mixups under contention),
    stats() callable mid-flight from caller threads (the
    unguarded-shared-state fix), and a clean close() that leaves no
    dispatcher thread behind."""
    import threading
    from roc_tpu.serve.export import build_predictor
    from roc_tpu.serve.server import Server
    ds, tr, _ = sgc_rig
    pred = build_predictor(_sgc_model(), ds, _config(),
                           params=tr.params, backend="auto")
    V = ds.graph.num_nodes
    solo = np.concatenate([pred.query([i]) for i in range(V)])
    n_threads, n_queries = 8, 25
    errors: list = []
    mismatches: list = []

    def client(seed):
        rng = np.random.default_rng(seed)
        try:
            for q in range(n_queries):
                ids = rng.integers(0, V, size=int(rng.integers(1, 40)))
                got = srv.submit(ids).result(timeout=30)
                if not np.array_equal(got, solo[ids]):
                    mismatches.append((seed, q, ids))
                if q % 7 == 0:
                    srv.stats()     # caller-thread read under load
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append((seed, e))

    with Server(pred, max_wait_ms=1.0) as srv:
        threads = [threading.Thread(target=client, args=(s,),
                                    name=f"client{s}")
                   for s in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        assert not any(t.is_alive() for t in threads)
        stats = srv.stats()
    assert not errors, errors[:3]
    assert not mismatches, mismatches[:3]
    assert stats["n_queries"] == n_threads * n_queries
    # clean shutdown: the dispatcher thread is gone, futures all done
    assert not srv._thread.is_alive()
    # and a submit after close fails fast instead of hanging
    with pytest.raises(RuntimeError):
        srv.submit([0]).result()


def test_close_rejects_late_submit_typed_serve_closed(sgc_rig):
    """ISSUE-13 satellite (rides next to the 8-thread stress test):
    ``close()`` rejects late ``submit()`` with the TYPED ServeClosed —
    a subclass of the old RuntimeError contract — and submitters
    RACING the close always resolve typed or with correct rows, never
    by hanging on a dispatcher that already exited."""
    import threading
    from roc_tpu.serve.errors import ServeClosed
    from roc_tpu.serve.export import build_predictor
    from roc_tpu.serve.server import Server
    ds, tr, _ = sgc_rig
    pred = build_predictor(_sgc_model(), ds, _config(),
                           params=tr.params, backend="auto")
    solo = pred.query(np.arange(20))
    srv = Server(pred, max_wait_ms=0.5)
    outcomes: list = []

    def spam(seed):
        for q in range(40):
            fut = srv.submit([q % 20])
            try:
                rows = fut.result(timeout=30)
                outcomes.append(("ok", q % 20, rows))
            except ServeClosed:
                outcomes.append(("closed", q % 20, None))

    threads = [threading.Thread(target=spam, args=(s,))
               for s in range(3)]
    for t in threads:
        t.start()
    srv.close()     # races the spammers on purpose
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads)
    assert len(outcomes) == 3 * 40
    for kind, i, rows in outcomes:
        if kind == "ok":
            assert np.array_equal(rows, solo[[i]])
    # after close the rejection is deterministic AND typed
    with pytest.raises(ServeClosed):
        srv.submit([0]).result()
    assert srv.stats()["n_rejected_closed"] >= 1


def test_server_oversized_and_error_paths(sgc_rig):
    from roc_tpu.serve.export import build_predictor
    from roc_tpu.serve.server import Server
    ds, tr, ref = sgc_rig
    pred = build_predictor(_sgc_model(), ds, _config(),
                           params=tr.params, backend="auto",
                           buckets=(1, 8))
    with Server(pred, max_wait_ms=0.0) as srv:
        # larger than the biggest bucket: split into chunks upstream
        out = srv.query(np.arange(50))
        assert np.abs(out - ref[:50]).max() <= 1e-5
        with pytest.raises(ValueError):
            srv.submit([ds.graph.num_nodes + 5]).result()
    with pytest.raises(RuntimeError):
        srv.submit([0]).result()


# --------------------------------------------------- zero-new-compiles

def test_cold_server_zero_new_compiles(sgc_rig, tmp_path):
    """THE acceptance criterion: a server process started from the
    exported artifact answers its first query with zero new compiled
    programs — every serve program is a persistent-cache warm hit, no
    new serve entry appears in the cache, and the worker's compile
    events' program_key set is contained in the manifest's."""
    from roc_tpu.serve.export import export_trainer
    ds, tr, _ = sgc_rig
    cache = str(tmp_path / "cache")
    os.makedirs(cache)
    art = str(tmp_path / "artifact")
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["ROC_TPU_CACHE_DIR"] = cache
    env["ROC_TPU_CACHE_MIN_SECS"] = "0"
    events = str(tmp_path / "events.jsonl")
    env["ROC_TPU_EVENTS"] = events
    # export in a CHILD too, so the parent process's already-compiled
    # jits cannot mask a cold server compile
    code = (
        "import numpy as np, jax\n"
        "from roc_tpu.utils.compile_cache import enable_compile_cache\n"
        "enable_compile_cache()\n"
        "from roc_tpu.core.graph import synthetic_dataset\n"
        "from roc_tpu.models.sgc import build_sgc\n"
        "from roc_tpu.train.trainer import Trainer, TrainConfig\n"
        "from roc_tpu.serve.export import export_trainer\n"
        "ds = synthetic_dataset(num_nodes=300, avg_degree=6, "
        "in_dim=24, num_classes=5, seed=0)\n"
        "tr = Trainer(build_sgc([24, 5], k=2, dropout_rate=0.5), ds, "
        "TrainConfig(verbose=False, symmetric=True))\n"
        f"export_trainer(tr, ds, {art!r})\n"
        "print('EXPORT_OK')\n")
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, timeout=240,
                       env=env, cwd=_REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "EXPORT_OK" in r.stdout
    before = set(os.listdir(cache))
    r = subprocess.run([sys.executable, _WORKER, art],
                       capture_output=True, text=True, timeout=240,
                       env=env, cwd=_REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "WORKER_OK" in r.stdout
    new = set(os.listdir(cache)) - before
    new_serve = sorted(f for f in new if _SERVE_ENTRY.search(f)
                       and f.endswith("-cache"))
    assert not new_serve, (
        f"cold server compiled NEW serve programs: {new_serve}")
    man = json.load(open(os.path.join(art, "serve_manifest.json")))
    live = {json.loads(line).get("program_key")
            for line in open(events)
            if '"cat": "compile"' in line}
    live.discard(None)
    serve_live = {k for k in live if k.startswith("serve_")}
    assert serve_live <= set(man["program_keys"]), (
        f"live-only serve keys: "
        f"{sorted(serve_live - set(man['program_keys']))}")


# --------------------------------------------------------- invalidation

def test_incremental_invalidation_parity(sgc_rig):
    """Edge append → incremental k-hop recompute equals a full rebuild
    of the propagation tables on the mutated graph, and the served
    logits follow."""
    from roc_tpu.core.graph import Graph
    from roc_tpu.serve.export import build_predictor
    from roc_tpu.serve.propagation import PropagationCache
    ds, tr, _ = sgc_rig
    pred = build_predictor(_sgc_model(), ds, _config(),
                           params=tr.params, backend="auto")
    u, v = 3, 250
    n = pred.invalidate([u, v], [v, u])
    assert n > 0
    g2 = Graph(row_ptr=pred.cache.row_ptr.copy(),
               col_idx=pred.cache.col_idx.copy())
    rebuilt = PropagationCache.build(g2, pred.cache.ops,
                                     np.asarray(ds.features))
    assert np.abs(pred.cache.table - rebuilt.table).max() <= 1e-5
    # far rows (outside the 2-hop neighborhood) were never touched:
    # served logits must still match a predictor built on the rebuilt
    # tables exactly
    pred2 = build_predictor(_sgc_model(), ds, _config(),
                            params=tr.params, backend="precomputed",
                            cache=rebuilt)
    a = pred.query(np.arange(ds.graph.num_nodes))
    b = pred2.query(np.arange(ds.graph.num_nodes))
    assert np.abs(a - b).max() <= 1e-5


def test_incremental_invalidation_parity_fused_relu():
    """The fused-activation path of the incremental walk: a prefix
    containing ``fused_aggregate(activation=relu)`` (what
    fuse_norm_aggregate makes of norm→agg→norm→relu) must recompute
    affected rows THROUGH the relu — the fancy-index ``out=`` form
    silently skipped it (review finding)."""
    from roc_tpu.core.graph import Graph
    from roc_tpu.models.builder import Model
    from roc_tpu.ops.dense import AC_MODE_NONE
    from roc_tpu.serve.export import build_predictor
    from roc_tpu.serve.propagation import PropagationCache
    ds = _dataset()
    m = Model(in_dim=24)
    t = m.input()
    t = m.indegree_norm(t)
    t = m.scatter_gather(t)
    t = m.indegree_norm(t)
    t = m.relu(t)
    t = m.dropout(t, 0.5)
    t = m.linear(t, 5, AC_MODE_NONE)
    m.softmax_cross_entropy(t)
    # aggr_fuse='auto' (default) folds the chain into ONE
    # fused_aggregate op carrying activation='relu'
    pred = build_predictor(m, ds, _config(), backend="auto")
    assert pred.flavor == "akx"
    assert any(op.get("activation") == "relu"
               for op in pred.cache.ops)
    u, v = 3, 250
    pred.invalidate([u, v], [v, u])
    g2 = Graph(row_ptr=pred.cache.row_ptr.copy(),
               col_idx=pred.cache.col_idx.copy())
    rebuilt = PropagationCache.build(g2, pred.cache.ops,
                                     np.asarray(ds.features))
    assert np.abs(pred.cache.table - rebuilt.table).max() <= 1e-5


def test_predict_node_ids_out_of_range_raises(gcn_rig):
    """Both trainers reject out-of-range ids instead of jnp.take's
    silent NaN fill — one contract across the serve gather paths."""
    ds, tr, _ = gcn_rig
    with pytest.raises(ValueError, match="out of range"):
        tr.predict(node_ids=[ds.graph.num_nodes])


def test_invalidation_refused_for_table_flavor():
    from roc_tpu.serve.propagation import logits_table_cache
    cache = logits_table_cache(np.zeros((4, 2), np.float32))
    with pytest.raises(NotImplementedError):
        cache.add_edges([0], [1])


# ------------------------------------------------------- programspace

def test_serve_rig_enumerated_and_prewarmable(tmp_path):
    """The sgc_serve rig: enumeration matches the committed program
    budget, candidate AOT closures compile, and warm_candidates
    reports them cold-then-warm against a fresh cache."""
    from roc_tpu.analysis.findings import load_program_budget
    from roc_tpu.analysis.programspace import (build_rig_dataset,
                                               build_rig_trainer,
                                               enumerate_programs,
                                               rig_configs)
    spec = rig_configs()["sgc_serve"]
    assert spec.serve == "precomputed"
    ds = build_rig_dataset()
    space = enumerate_programs(spec, dataset=ds)
    budget = load_program_budget(
        os.path.join(_REPO, "scripts", "lint_baseline.json"))
    assert space.program_count == budget["sgc_serve"]
    assert all(e.slot.startswith("serve_precomputed_akx:")
               for e in space.entries)
    pred = build_rig_trainer(spec, dataset=ds)
    cache = str(tmp_path / "cache")
    os.makedirs(cache)
    # pred.warm() routes through enable_compile_cache(cache) so the
    # cold/warm listdir accounting watches the dir jax really writes
    rep = pred.warm(cache_dir=cache, name="sgc_serve_test")
    assert rep["failed"] == 0
    assert rep["compile_cold"] == rep["programs"]
    rep2 = pred.warm(cache_dir=cache, name="sgc_serve_test")
    assert rep2["compile_warm_hits"] == rep2["programs"]


def test_precompute_split_shapes():
    """The split detector: SGC matches, GCN (graph ops below the
    head) and APPNP (params before the propagation) do not."""
    from roc_tpu.models.appnp import build_appnp
    split = _sgc_model().precompute_split()
    assert split is not None
    prefix, head = split
    assert sum(op.kind == "scatter_gather" for op in prefix) == 2
    assert all(op.kind not in ("scatter_gather", "gat")
               for op in head._ops)
    assert _gcn_model().precompute_split() is None
    assert build_appnp([24, 8, 5], k=2).precompute_split() is None


def test_model_spec_roundtrip():
    m = _sgc_model()
    from roc_tpu.models.builder import Model
    m2 = Model.from_spec(json.loads(json.dumps(m.to_spec())))
    assert [(o.kind, o.inputs, o.dim, o.param, o.attrs)
            for o in m._ops] == \
           [(o.kind, o.inputs, o.dim, o.param, o.attrs)
            for o in m2._ops]
    assert m2._loss_op == m._loss_op
