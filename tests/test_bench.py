"""The staged benchmark protocol (bench.py) — CPU smoke.

bench.py is the driver's scoring entry point; these tests pin its
always-one-JSON-line contract and the graceful-degradation behavior
the staged design exists for, without touching the TPU (--cpu)."""

import json
import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_BENCH = os.path.join(_REPO, "bench.py")


def _run(args, timeout=300, art_dir=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if art_dir:
        # keep test runs out of the repo's recorded artifacts
        env["ROC_TPU_BENCH_ARTIFACTS"] = art_dir
    return subprocess.run(
        [sys.executable, _BENCH] + args, capture_output=True,
        text=True, timeout=timeout, cwd=_REPO, env=env)


def _last_json(out: str) -> dict:
    lines = [l for l in out.splitlines() if l.strip().startswith("{")]
    assert lines, out
    return json.loads(lines[-1])


@pytest.mark.slow
def test_small_stage_emits_json_line(tmp_path):
    r = _run(["--cpu", "--stages", "small", "--epochs", "2"],
             art_dir=str(tmp_path))
    assert r.returncode == 0, r.stderr[-2000:]
    line = _last_json(r.stdout)
    assert line["unit"] == "ms"
    assert line["value"] > 0
    assert line["stage"] == "small"
    # CPU runs must never be recorded as baselines
    assert line.get("baseline") != "recorded_now"


def test_unknown_stage_still_prints_contract_line(tmp_path):
    r = _run(["--cpu", "--stages", "nope"], art_dir=str(tmp_path))
    line = _last_json(r.stdout)
    assert line["value"] is None
    assert "unknown stages" in line["error"]


def test_depleted_deadline_degrades_to_skip(tmp_path):
    """A deadline too small for any stage must yield the JSON contract
    line with per-stage skip errors — never a crash or silence."""
    r = _run(["--cpu", "--stages", "small", "--deadline", "30"],
             art_dir=str(tmp_path))
    line = _last_json(r.stdout)
    assert line["value"] is None
    assert "skipped" in line["stages"]["small"]["error"]


@pytest.mark.slow
def test_headline_metric_unsuffixed_with_dtype_field(tmp_path):
    """Non-fp32 runs keep the unsuffixed headline metric name but must
    carry an explicit dtype field (a precision-policy speedup is never
    a hidden claim)."""
    r = _run(["--cpu", "--stages", "small", "--epochs", "2",
              "--dtype", "mixed"], art_dir=str(tmp_path))
    assert r.returncode == 0, r.stderr[-2000:]
    line = _last_json(r.stdout)
    assert not line["metric"].endswith("_mixed")
    assert line["dtype"] == "mixed"


@pytest.mark.slow
def test_random_label_accuracy_is_labeled(tmp_path):
    """The synthetic-graph accuracies are not a quality signal and the
    stage record must say so (VERDICT r3 weak #4)."""
    r = _run(["--cpu", "--stages", "small", "--epochs", "2"],
             art_dir=str(tmp_path), timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    line = _last_json(r.stdout)
    small = line["stages"]["small"]
    assert small.get("labels") == "synthetic_random"
    assert "train_acc" not in small  # only the labeled keys remain
    assert "random_label_train_acc" in small


def test_promotes_in_round_stage_record_when_all_stages_fail(tmp_path):
    """When the relay cannot be claimed at snapshot time, the freshest
    on-chip GCN stage record from bench_stages.jsonl is promoted into
    the headline line with provenance="in_round_stage" — BENCH must
    never be null while real on-chip records exist (VERDICT r4 #2)."""
    import time as _time
    now = _time.strftime("%Y-%m-%dT%H:%M:%S%z")
    rec = {"stage": "full", "t": now, "ok": True,
           "result": {"platform": "tpu", "device_kind": "TPU v5 lite",
                      "V": 232965, "E": 114848857,
                      "layers": "602-256-41", "impl": "sectioned",
                      "dtype": "mixed", "epoch_ms": 2359.25}}
    base = {"full_graph_gcn_reddit_scale_epoch_time": {
        "platform": "tpu", "dtype": "float32", "impl": "ell",
        "epoch_ms": 7920.78, "recorded": "2026-07-29T21:07:04+0000"}}
    (tmp_path / "bench_stages.jsonl").write_text(json.dumps(rec) + "\n")
    (tmp_path / "measured_baselines.json").write_text(json.dumps(base))
    # no --cpu: promotion is a tunnel-weather path; deadline too small
    # for any stage so nothing ever touches a backend
    r = _run(["--deadline", "1"], art_dir=str(tmp_path), timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    line = _last_json(r.stdout)
    assert line["value"] == 2359.25
    assert line["provenance"] == "in_round_stage"
    assert line["vs_baseline"] == pytest.approx(7920.78 / 2359.25, rel=1e-3)
    assert line["live_errors"]  # the real failure is still on record


def test_stderr_dedupe_filter(capsys):
    """Satellite: repeated identical third-party stderr warning lines
    (re-dated across probe attempts — the r05 tail was 5x the same
    'Platform axon is experimental' warning) forward once plus a
    dedup note; this repo's own '# ' diagnostic lines NEVER dedupe
    (heartbeats and retry notes are the evidence the tail exists
    for)."""
    import io
    sys.path.insert(0, _REPO)
    import bench
    bench._STDERR_SEEN.clear()
    warn = ("WARNING:2026-07-31 19:%02d:54,854:jax._src.xla_bridge:905:"
            " Platform 'axon' is experimental and not all JAX "
            "functionality may be correctly supported!")
    tb = ["Traceback (most recent call last):",
          '  File "bench.py", line 123, in run_child',
          "ValueError: shape (8, 3)"]
    lines = [warn % 41, "# stage probe: timeout after 150s (150.1s)",
             warn % 45, warn % 48, warn % 52,
             "# stage probe: timeout after 150s (150.1s)"] + tb + tb
    counts = {}
    bench._forward_stderr(io.StringIO("\n".join(lines) + "\n"), counts)
    err = capsys.readouterr().err
    assert err.count("Platform 'axon' is experimental") == 2
    assert "# [stderr dedup] repeat suppressed" in err
    assert counts["suppressed"] == 3
    assert err.count("# stage probe: timeout after 150s") == 2
    # tracebacks/error text are NOT dedupe-eligible: two crashes that
    # share normalized frame lines must both arrive whole
    for line in tb:
        assert err.count(line) == 2, line
        assert bench._dedup_key(line) is None
    # normalization: differing timestamps of one warning still dedupe
    assert bench._dedup_key(warn % 41) == bench._dedup_key(warn % 45)
    assert bench._dedup_key("# ours (12s)") is None
    bench._STDERR_SEEN.clear()


@pytest.mark.slow
def test_small_stage_records_sentinel_verdict(tmp_path):
    """The bench headline line carries the regression-sentinel verdict
    (roc_tpu/obs/sentinel.py) so every BENCH_*.json round records its
    own check against the trajectory."""
    r = _run(["--cpu", "--stages", "small", "--epochs", "2"],
             art_dir=str(tmp_path))
    assert r.returncode == 0, r.stderr[-2000:]
    line = _last_json(r.stdout)
    assert "sentinel" in line, line
    assert line["sentinel"]["verdict"] in ("ok", "no_history",
                                           "regression")


def test_cpu_run_never_promotes(tmp_path):
    """--cpu failures are local bugs, not tunnel weather: the null
    contract line must survive even with promotable records on disk."""
    rec = {"stage": "full", "t": "2026-07-30T05:08:58+0000", "ok": True,
           "result": {"platform": "tpu", "epoch_ms": 2359.25,
                      "dtype": "mixed"}}
    (tmp_path / "bench_stages.jsonl").write_text(json.dumps(rec) + "\n")
    r = _run(["--cpu", "--stages", "small", "--deadline", "30"],
             art_dir=str(tmp_path))
    line = _last_json(r.stdout)
    assert line["value"] is None


def test_micro_only_run_never_promotes(tmp_path):
    """Promotion must not fire for runs that never wanted a GCN stage:
    a probe-failed micro-only run keeps the null contract line even
    with promotable records on disk."""
    rec = {"stage": "full", "t": "2026-07-30T05:08:58+0000", "ok": True,
           "result": {"platform": "tpu", "epoch_ms": 2359.25,
                      "dtype": "mixed"}}
    (tmp_path / "bench_stages.jsonl").write_text(json.dumps(rec) + "\n")
    r = _run(["--stages", "micro", "--deadline", "1"],
             art_dir=str(tmp_path), timeout=120)
    line = _last_json(r.stdout)
    assert line["value"] is None
    assert "provenance" not in line


def test_probe_timeout_leaves_partial_and_aborts_same_phase(tmp_path):
    """A timed-out probe must leave a heartbeat-dated partial result
    (where it died, normalized) instead of a bare error, and two
    consecutive deaths at the SAME phase must abort the retry loop —
    the r04/r05 failure burned the whole deadline re-dying at the
    identical phase five times."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               ROC_TPU_BENCH_ARTIFACTS=str(tmp_path),
               # dies in interpreter startup, BEFORE any progress
               # marker: 0.05 s is under bare `python -c pass` wall on
               # any host, where the old 1 s let a warm-page-cache jax
               # import finish and the probe SUCCEED (observed flake)
               ROC_TPU_BENCH_PROBE_TIMEOUT="0.05",
               ROC_TPU_BENCH_PROBE_INTERVAL="0")     # no retry sleep
    r = subprocess.run(
        [sys.executable, _BENCH, "--cpu", "--stages", "probe",
         "--probe-retries", "5", "--deadline", "600"],
        capture_output=True, text=True, timeout=240, cwd=_REPO,
        env=env)
    line = _last_json(r.stdout)
    assert line["value"] is None
    recs = [json.loads(l) for l in
            (tmp_path / "bench_stages.jsonl").read_text().splitlines()]
    probes = [x for x in recs if x.get("stage") == "probe"]
    # same-phase abort after the second identical death, not 6
    # attempts.  Under heavy host load the FIRST attempt can die
    # inside the 1 s window before writing its progress phase, which
    # legitimately costs one extra attempt before two phases tie —
    # so 3 is tolerated, 6 (the r04/r05 deadline burn) never is.
    assert 2 <= len(probes) <= 3, [p.get("error") for p in probes]
    for p in probes[-2:]:
        assert p["partial"]["last_phase"], p
        assert "t" in p["partial"]
    aborts = [x for x in recs if x.get("stage") == "probe_abort"]
    assert len(aborts) == 1
    assert aborts[0]["attempts"] == len(probes)


def test_stale_record_not_promoted(tmp_path):
    """The stage log is append-only across rounds: records past the
    promotion age window yield an honest null, never a replay of an
    old round's number."""
    rec = {"stage": "full", "t": "2026-07-01T05:08:58+0000", "ok": True,
           "result": {"platform": "tpu", "epoch_ms": 2359.25,
                      "dtype": "mixed"}}
    (tmp_path / "bench_stages.jsonl").write_text(json.dumps(rec) + "\n")
    r = _run(["--deadline", "1"], art_dir=str(tmp_path), timeout=120)
    line = _last_json(r.stdout)
    assert line["value"] is None
    # ...unless the caller widens the window explicitly
    r = _run(["--deadline", "1", "--promote-max-age-h", "100000"],
             art_dir=str(tmp_path), timeout=120)
    line = _last_json(r.stdout)
    assert line["value"] == 2359.25


def test_gcn_stage_checkpoint_resume(tmp_path):
    """ISSUE-13 satellite (ROADMAP checkpoint-aware bench probe): a
    GCN stage child that died mid-round leaves a rotation checkpoint;
    the retry attempt RESUMES from it (resumed_from_epoch in the
    result) instead of re-training cold, and _clear_gcn_checkpoints
    keeps rounds from contaminating each other.  Driven through
    bench.child_gcn in a subprocess with a tiny rig."""
    code = (
        "import os, sys, types, json\n"
        f"os.environ['ROC_TPU_BENCH_ARTIFACTS'] = {str(tmp_path)!r}\n"
        f"sys.path.insert(0, {_REPO!r})\n"
        "import bench\n"
        "args = types.SimpleNamespace(cpu=True, layers='12-8-3',\n"
        "    impl='ell', chunk=512, dtype='float32', epochs=1,\n"
        "    stage='small')\n"
        "r1 = bench.child_gcn(args, 256, 2048)\n"
        "assert r1['resumed_from_epoch'] is None, r1\n"
        "# the async checkpoint-cost row landed\n"
        "assert r1['ckpt_block_ms'] is not None, r1\n"
        "assert r1['ckpt_save_ms'] is not None, r1\n"
        "# the post-warmup rotation checkpoint exists (v3 committed\n"
        "# directory)\n"
        "import glob\n"
        "cks = glob.glob(bench._gcn_ck_prefix('small')\n"
        "                + '.*/MANIFEST.json')\n"
        "assert cks, 'no rotation checkpoint written'\n"
        "# attempt 2 (same parent round): resumes from the rotation\n"
        "args2 = types.SimpleNamespace(cpu=True, layers='12-8-3',\n"
        "    impl='ell', chunk=512, dtype='float32', epochs=1,\n"
        "    stage='small')\n"
        "r2 = bench.child_gcn(args2, 256, 2048)\n"
        "assert r2['resumed_from_epoch'] is not None, r2\n"
        "assert r2['resumed_from_epoch'] >= 2, r2\n"
        "# fresh ROUND: the parent clears the rotation first\n"
        "bench._clear_gcn_checkpoints('small')\n"
        "assert not glob.glob(bench._gcn_ck_prefix('small') + '.*')\n"
        "# the resume evidence rides the progress file into partials\n"
        "prog = bench._read_probe_progress()\n"
        "assert bench._progress_resumed_epoch(prog) == "
        "r2['resumed_from_epoch']\n"
        "print('RESUME_OK')\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True, timeout=300,
                       cwd=_REPO, env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-3000:]
    assert "RESUME_OK" in r.stdout
