#!/usr/bin/env python
"""Headline benchmark: full-graph GCN epoch time at Reddit scale.

Protocol (BASELINE.md): the reference repo publishes no numbers, so the
recorded baseline is the reference's canonical workload shape — the
2-layer 602-256-41 GCN on Reddit (232,965 nodes, ~114.8M edges with self
edges, ``example_run.sh:1`` / ``test.sh:8``) — run full-graph,
full-batch with dropout 0.5, Adam, masked softmax-CE, exactly like
``gnn.cc:99-111``'s epoch loop.  When real Reddit data is not available,
a deterministic synthetic graph with matched V/E/degree skew is used;
epoch time is independent of edge identity.

Staged protocol (the TPU is reached through a single-claim tunnel that
can be busy, slow, or hang): the benchmark is a sequence of stages run
as child subprocesses, each under its own timeout inside a global
deadline, and **every stage's result is persisted the moment it
exists** — a timeout at a later stage can no longer yield zero data:

  probe   claim the backend + one matmul (is the chip reachable at
          all?); on failure, retries are spread ~3.5 min apart across
          the whole deadline (a wedged relay recovers on the ~30 min
          scale), after first reaping any stale claim-holding processes
  small   headline GCN at small scale (V=2048, E=32k) — the cheapest
          stage that yields a non-null headline value runs first
  full    headline GCN at Reddit scale
  micro   neighbor-aggregation race at reduced scale
          (V=50k, E=10M, F=256): ms + GB/s per impl

Artifacts:
  benchmarks/bench_stages.jsonl       one line per stage attempt
  benchmarks/measured_baselines.json  first successful TPU measurement
                                      per metric, with provenance

stdout gets ONE JSON line at the end:
  {"metric": ..., "value": ..., "unit": "ms", "vs_baseline": ...,
   "stage": <furthest completed headline stage>, "stages": {...}}

vs_baseline: ratio of the recorded baseline for this metric to this
run's value; >1.0 is faster.  First successful run records itself as
the baseline and reports 1.0 with "baseline": "recorded_now".

The child holding a TPU claim is terminated with SIGTERM, never SIGKILL
first — hard-killing a claim holder can wedge the tunnel relay for
subsequent processes.
"""

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import time

import numpy as np

REDDIT_NODES = 232_965
REDDIT_EDGES = 114_848_857  # 114,615,892 + 232,965 self edges

METRIC_FULL = "full_graph_gcn_reddit_scale_epoch_time"
METRIC_SMALL = "full_graph_gcn_small_epoch_time"
METRIC_MICRO = "neighbor_aggregation_reduced"
METRIC_SERVE = "serve_microbatch_latency"

_HERE = os.path.dirname(os.path.abspath(__file__))
# tests (and any sandboxed run) point this at a temp dir so stage
# attempts / baselines never dirty the repo's recorded artifacts
_ART_DIR = (os.environ.get("ROC_TPU_BENCH_ARTIFACTS")
            or os.path.join(_HERE, "benchmarks"))
_BASELINES_PATH = os.path.join(_ART_DIR, "measured_baselines.json")
_STAGES_PATH = os.path.join(_ART_DIR, "bench_stages.jsonl")

# (name, default child timeout s, minimum useful budget s)
STAGES = (("probe", 150.0, 40.0),
          ("micro", 420.0, 150.0),
          ("small", 300.0, 150.0),
          ("full", 900.0, 420.0),
          ("serve", 420.0, 120.0))

# seconds between probe attempt STARTS while the tunnel is down — a
# wedged relay recovers on the ~30 min scale, so probes are spread
# across the whole deadline instead of front-loaded backoff (the r03
# failure mode: four probes bunched into the first 6 minutes).
# ROC_TPU_BENCH_PROBE_INTERVAL / ROC_TPU_BENCH_PROBE_TIMEOUT override
# the spacing and the probe child timeout (tests, tunnel tuning).
_PROBE_INTERVAL = 210.0
_PROBE_PROGRESS = "probe_progress.txt"


def _probe_interval() -> float:
    try:
        return float(os.environ.get("ROC_TPU_BENCH_PROBE_INTERVAL",
                                    _PROBE_INTERVAL))
    except ValueError:
        return _PROBE_INTERVAL


def _light_obs_imports() -> None:
    """Make ``roc_tpu.obs`` importable in the PARENT without executing
    the package's heavy ``__init__`` (which imports jax).  The parent
    is deliberately import-light — all jax work lives in stage
    children under per-stage timeouts, so a wedged/slow jax import
    must never eat the parent's deadline unobserved.  ``roc_tpu/obs``
    and its modules are stdlib-only, so a namespace stub for the
    parent package is all the import system needs.  No-op when the
    real package is already loaded (in-process tests, children)."""
    if "roc_tpu" in sys.modules:
        return
    import types
    pkg = types.ModuleType("roc_tpu")
    pkg.__path__ = [os.path.join(_HERE, "roc_tpu")]
    sys.modules["roc_tpu"] = pkg


def build_parser():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=REDDIT_NODES)
    ap.add_argument("--edges", type=int, default=REDDIT_EDGES)
    ap.add_argument("--layers", type=str, default="602-256-41")
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--chunk", type=int, default=512)
    # auto resolves to 'sectioned' at Reddit scale / 'ell' below VMEM
    # table size (the CLI default too, roc_tpu/train/cli.py) — the
    # data-chosen production path: sectioned measured 2708 ms/epoch vs
    # ell's 7920.8 at full Reddit scale (vs_baseline 2.93; 2359 ms
    # with --dtype mixed -> 3.36 vs the recorded fp32 ell baseline)
    ap.add_argument("--impl", type=str, default="auto")
    # mixed (fp32 master params + bf16 compute) is the production
    # default — the headline line carries explicit dtype/impl fields
    # for both the run and the baseline it compares against
    ap.add_argument("--dtype", type=str, default="mixed")
    # small before full: the cheapest stage that yields a non-null
    # headline value runs first, so a late tunnel recovery still lands
    # a number; the diagnostic stages (micro race, serve load gen)
    # run after the headline GCN stages
    ap.add_argument("--stages", type=str,
                    default="probe,small,full,micro,serve",
                    help="comma list of stages to run, in order")
    ap.add_argument("--small", action="store_true",
                    help="shorthand for --stages probe,small (CI)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (skip the TPU claim); "
                         "results are NOT recorded as baselines")
    ap.add_argument("--deadline", type=float, default=1380.0,
                    help="global wall-clock budget (s); must stay under "
                         "the driver's own timeout so the final JSON "
                         "line always gets printed")
    ap.add_argument("--promote-max-age-h", type=float, default=48.0,
                    help="max age of a bench_stages.jsonl record "
                         "eligible for in_round_stage promotion when "
                         "every live stage fails; 48h spans one "
                         "build-round cadence (a record from the "
                         "previous session is attributable — its "
                         "timestamp rides along as "
                         "provenance_recorded — while a week-old one "
                         "masks a persistently dead tunnel)")
    ap.add_argument("--probe-retries", type=int, default=8,
                    help="max extra probe attempts; attempts are "
                         "spread ~3.5 min apart across the whole "
                         "deadline (a wedged tunnel recovers on the "
                         "~30 min scale), stopping when a success "
                         "could no longer fit a measurement stage")
    # internal
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--stage", type=str, default=None,
                    help=argparse.SUPPRESS)
    return ap


# ---------------------------------------------------------------- artifacts

def _append_stage(record: dict) -> None:
    os.makedirs(os.path.dirname(_STAGES_PATH), exist_ok=True)
    with open(_STAGES_PATH, "a") as f:
        f.write(json.dumps(record) + "\n")


def _load_baselines() -> dict:
    try:
        with open(_BASELINES_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _record_baseline(metric: str, entry: dict) -> bool:
    """Record ``entry`` as the baseline for ``metric`` if none exists.
    Returns True if this call recorded it."""
    db = _load_baselines()
    if metric in db:
        return False
    db[metric] = entry
    os.makedirs(os.path.dirname(_BASELINES_PATH), exist_ok=True)
    tmp = _BASELINES_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(db, f, indent=1, sort_keys=True)
    os.replace(tmp, _BASELINES_PATH)
    return True


def _now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S%z")


_ANSI_RE = re.compile(r"\x1b\[[0-9;]*[A-Za-z]|\x1b\][^\x07\x1b]*(\x07|\x1b\\)?")
# swallow everything to whitespace or a ": "-style suffix separator —
# userinfo (user:token@host) must not survive the redaction
_URL_RE = re.compile(r"https?://\S+?(?=:\s|[\s]|$)")


def _errstr(e: BaseException, limit: int = 300) -> str:
    """First line of the exception, ANSI escapes stripped, endpoint
    URLs redacted, truncated — what gets persisted into
    machine-readable artifacts (a raw MosaicError once polluted
    measured_baselines.json with escape sequences and a tunnel URL)."""
    s = _ANSI_RE.sub("", f"{type(e).__name__}: {e}")
    s = _URL_RE.sub("<endpoint>", s)
    first = s.splitlines()[0] if s.splitlines() else s
    return first[:limit]


# ------------------------------------------------------- claim hygiene

# Leftover processes from earlier work sessions that can hold or queue
# the single-claim TPU tunnel: crashed bench children, ad-hoc probes,
# tpu_watch loops (each watch attempt queues a claim for up to 180 s
# and a killed claim holder can wedge the relay for everyone after it).
# Patterns are THIS repo's absolute script paths — `bench.py` of some
# unrelated project, or an editor with the name on its command line,
# must never match (round-4 advisor finding).
_STALE_CMD_PATTERNS = tuple(os.path.join(_HERE, rel) for rel in (
    "bench.py",
    "scripts/tpu_watch",
    "benchmarks/micro_agg.py",
    "benchmarks/micro_serve.py",
    "benchmarks/model_zoo.py",
    "benchmarks/calibrate.py",
    "benchmarks/compile_probe.py",
    "__graft_entry__.py",
))


def _ppid(pid: int) -> int:
    try:
        with open(f"/proc/{pid}/stat") as f:
            return int(f.read().rsplit(")", 1)[1].split()[1])
    except (OSError, ValueError, IndexError):
        return -1


def _orphaned(pid: int) -> bool:
    """The launching session is gone: reparented to init, or the
    parent vanished mid-check.  Deliberately NOT extended to
    comm-based subreaper heuristics (tmux server / systemd): `tmux
    new-window 'python bench.py'` runs as a DIRECT live child of the
    tmux server, so killing on that evidence would reap legitimate
    measurements.  A corpse adopted by a subreaper is the accepted
    gap — watch loops (the r03 starvation class) are reaped on age
    alone regardless of parentage."""
    ppid = _ppid(pid)
    if ppid == 1:
        return True
    try:
        os.stat(f"/proc/{ppid}")
    except OSError:
        return True  # parent vanished between reads
    return False


def _ancestors_and_self() -> set:
    pids = set()
    pid = os.getpid()
    while pid > 1 and pid not in pids:
        pids.add(pid)
        pid = _ppid(pid)
    return pids


_STALE_MIN_AGE_S = 120.0


def _pid_age_s(pid: int) -> float:
    try:
        return time.time() - os.stat(f"/proc/{pid}").st_mtime
    except OSError:
        return 0.0


def _reap_stale_tpu_processes(grace: float = None) -> list:
    """SIGTERM (then SIGKILL) stale processes that could hold the TPU
    tunnel claim, so the probe never queues behind this session's own
    corpses.  Matches only THIS repo's absolute script paths (plus
    anonymous ``python -`` probes whose stdout points at this repo's
    tpu_watch logs), and requires REAL staleness evidence before
    killing: the process must be orphaned (reparented to init or a
    subreaper — its launching shell/session is gone) AND older than
    ``_STALE_MIN_AGE_S``.  A
    concurrent legitimate bench launched from a live shell keeps its
    shell as parent and is spared, however long it has run; ``--cpu``
    runs never hold a claim and are spared unconditionally.  Returns
    ``[{pid, cmd}]`` for the stage record."""
    if grace is None:
        grace = _TERM_GRACE  # same claim-unwind budget as stage children
    keep = _ancestors_and_self()
    victims = []
    try:
        proc_entries = os.listdir("/proc")
    except OSError:
        return []
    for name in proc_entries:
        if not name.isdigit():
            continue
        pid = int(name)
        if pid in keep:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\x00", b" ") \
                    .decode("utf-8", "replace").strip()
        except OSError:
            continue
        if not cmd or "--cpu" in cmd.split():
            continue
        head = cmd.split()[0].rsplit("/", 1)[-1]
        # only interpreter/launcher processes are candidates: an editor
        # or `git diff bench.py` matching a pattern substring must
        # never be killed
        if head not in ("python", "python3", "sh", "bash", "dash",
                        "timeout"):
            continue
        def _matches(p: str) -> bool:
            if p in cmd:
                return True
            # `cd /root/repo && python bench.py` leaves a RELATIVE
            # path in cmdline — resolve argv tokens against the
            # process's own cwd so those corpses still match, without
            # ever matching another repo's same-named script
            try:
                cwd = os.readlink(f"/proc/{pid}/cwd")
            except OSError:
                return False
            return any(os.path.normpath(
                os.path.join(cwd, tok)).startswith(p)
                for tok in cmd.split() if not tok.startswith("-"))

        is_watch = _matches(os.path.join(_HERE, "scripts/tpu_watch"))
        if not is_watch and head in ("python", "python3", "timeout"):
            # ad-hoc watch probes are bare ``python -`` heredocs whose
            # stdout points at a tpu_watch log (default /tmp, or one
            # under this repo)
            try:
                link = os.readlink(f"/proc/{pid}/fd/1")
                is_watch = "tpu_watch" in os.path.basename(link)
            except OSError:
                pass
        is_meas = not is_watch and any(
            _matches(p) for p in _STALE_CMD_PATTERNS)
        # Watch loops are reaped on age alone: they re-queue a 180 s
        # tunnel claim forever and are NEVER a legitimate concurrent
        # measurement, even when their launching shell is still alive
        # (the r03 starvation mode).  Measurement runs additionally
        # need real staleness evidence — init-orphaned (their session
        # is gone) — so a long-running deliberate bench from a live
        # shell is always spared (round-4 advisor).
        stale = (is_watch or (is_meas and _orphaned(pid)))
        if stale and _pid_age_s(pid) >= _STALE_MIN_AGE_S:
            victims.append({"pid": pid, "cmd": cmd[:160]})
    for v in victims:
        try:
            os.kill(v["pid"], signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass
    deadline = time.time() + grace
    alive = [v["pid"] for v in victims]
    while alive and time.time() < deadline:
        time.sleep(0.5)
        alive = [p for p in alive if _pid_alive(p)]
    for p in alive:
        # the stale holder is already defunct as a claimant; a lingering
        # hung process blocks the tunnel harder than a SIGKILL risk does
        try:
            os.kill(p, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
    return victims


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


# ------------------------------------------------- probe progress file

def _probe_progress_path() -> str:
    return os.path.join(_ART_DIR, _PROBE_PROGRESS)


def _probe_note(msg: str) -> None:
    """Append a timestamped phase marker (child side) so a timed-out
    probe still tells WHERE it died — claim-wait vs compile vs matmul
    is diagnosable from the artifact alone."""
    try:
        os.makedirs(_ART_DIR, exist_ok=True)
        with open(_probe_progress_path(), "a") as f:
            f.write(f"{time.time():.1f} {msg}\n")
    except OSError:
        pass


def _read_probe_progress() -> list:
    try:
        with open(_probe_progress_path()) as f:
            return [line.rstrip("\n") for line in f][-8:]
    except OSError:
        return []


def _probe_phase(progress: list) -> str:
    """Normalized terminal phase of a (possibly dead) probe attempt:
    the last progress marker with timestamps/durations collapsed, so
    two attempts that died at the same point compare equal (the
    retry-abort signal — r04/r05 burned the whole deadline re-dying
    at the identical phase five times)."""
    if not progress:
        # run_child imports jax (for the compile cache) before the
        # first marker is written, so an empty file means the import
        # itself never finished
        return "no-progress (died in the jax/roc_tpu import)"
    last = progress[-1]
    txt = last.split(" ", 1)[1] if " " in last else last
    return re.sub(r"[0-9.]+", "N", txt)


def _progress_resumed_epoch(progress: list):
    """The epoch a GCN stage child reported resuming from
    (``resumed_from_epoch=N`` progress marker), or None."""
    for line in reversed(progress):
        m = re.search(r"resumed_from_epoch=(\d+)", line)
        if m:
            return int(m.group(1))
    return None


def _clear_gcn_checkpoints(stage: str) -> None:
    """Drop a previous ROUND's rotation before the first attempt —
    resume must only ever cross attempts of ONE parent invocation
    (a days-old checkpoint would silently skew the epoch count)."""
    import glob as _glob
    import shutil as _shutil
    # v3 checkpoint directories (<prefix>.<epoch>/ incl. the sync
    # probe's) plus any legacy .npz files from older rounds
    for p in _glob.glob(_gcn_ck_prefix(stage) + ".*"):
        try:
            if os.path.isdir(p):
                _shutil.rmtree(p)
            else:
                os.unlink(p)
        except OSError:
            pass


# ------------------------------------------- program-space preflight

def _programspace_preflight(timeout: float = 240.0):
    """Diff the auditor's CURRENT program-key sets against the cached
    warm state (``benchmarks/programspace_warm.json``, written by
    ``python -m roc_tpu.prewarm``).  Returns None when there is no
    cached warm state (nothing to guard), an empty dict when every
    warmed config's program set is unchanged (the persistent cache is
    still hot), or ``{config: n_new_keys}`` when a config's program
    set GREW — a probe on such a config would pay first-compile cost
    for every new program, exactly the blank-timeout class (r01-r05)
    this preflight refuses to re-enter.  The enumeration runs in a
    CPU child (``python -m roc_tpu.analysis --json`` forces the CPU
    rig itself); any preflight failure degrades to 'no guard' — the
    probe must never be blocked by a broken preflight."""
    # ONE path resolution + loader (utils/prewarm.py — jax-free at
    # import), shared with the prewarm writer so reader and writer
    # cannot drift; _ART_DIR honors the same ROC_TPU_BENCH_ARTIFACTS
    from roc_tpu.utils.prewarm import WARM_STATE_NAME, load_warm_state
    state = load_warm_state(os.path.join(_ART_DIR, WARM_STATE_NAME))
    if not state:
        return None
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    try:
        r = subprocess.run(
            [sys.executable, "-m", "roc_tpu.analysis", "--json",
             "--select", "compile-explosion,cache-key-drift"],
            capture_output=True, text=True, timeout=timeout, env=env,
            cwd=_HERE)
        payload = json.loads(r.stdout)
    except Exception as e:  # noqa: BLE001 - preflight is best-effort
        print(f"# programspace preflight unavailable: {_errstr(e)}",
              file=sys.stderr)
        return None
    grown = {}
    for rep in payload.get("program_space", []):
        cfg = rep.get("config")
        warmed = state.get(cfg)
        if not warmed:
            continue
        new = set(rep.get("keys", [])) - set(warmed.get("keys", []))
        if new:
            grown[cfg] = len(new)
    return grown


# -------------------------------------------------- relay health check

def _relay_health(port: int = None, timeout: float = 2.0) -> dict:
    """Cheap TCP pre-check of the axon relay's loopback endpoint so a
    dead relay yields a DISTINCT error from a held claim (VERDICT r4
    #2: 'claiming backend' timeouts were indistinguishable from a
    relay that was not even listening).  Diagnostic only — the probe
    still runs either way (a refused remote-compile port does not
    always imply the claim leg is down)."""
    import socket
    if port is None:
        port = int(os.environ.get("ROC_TPU_RELAY_PORT", "8113"))
    t0 = time.time()
    try:
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=timeout):
            state = "listening"
    except ConnectionRefusedError:
        state = "refused"
    except (socket.timeout, OSError) as e:
        state = f"unreachable: {type(e).__name__}"
    return {"port": port, "state": state,
            "elapsed_s": round(time.time() - t0, 2)}


def _same_platform_class(a, b) -> bool:
    """'tpu' and 'axon' are the same chip reached two ways (the relay
    reports either name depending on the claim path); cpu vs on-chip
    is the mismatch the guard exists for."""
    on_chip = {"tpu", "axon"}
    return a == b or (a in on_chip and b in on_chip)


def _baseline_compare_fields(entry, platform, epoch_ms: float) -> dict:
    """The ONE place a measured epoch is compared against a recorded
    baseline (live headline and in-round promotion both use it): a
    platform mismatch is labeled, never silently scored."""
    if entry is None:
        return {"baseline": "unrecorded"}
    if not _same_platform_class(entry.get("platform"), platform):
        return {"baseline": f"platform_mismatch: baseline is "
                            f"{entry.get('platform')}, this run is "
                            f"{platform}"}
    if entry.get("epoch_ms") not in (None, epoch_ms):
        return {"vs_baseline": round(float(entry["epoch_ms"]) / epoch_ms,
                                     3),
                "baseline_ms": entry["epoch_ms"],
                "baseline_recorded": entry.get("recorded", "?"),
                "baseline_dtype": entry.get("dtype"),
                "baseline_impl": entry.get("impl")}
    return {"baseline": "recorded_now"}


# ------------------------------------- in-round stage record promotion

def _promote_stage_record(args, stage_summary: dict, errs: dict):
    """When every live stage failed (relay wedged/claimed at snapshot
    time), promote the freshest on-chip GCN stage record from this
    round's ``bench_stages.jsonl`` into the headline line, marked
    ``"provenance": "in_round_stage"`` so the number is attributable
    but clearly not from this invocation (VERDICT r4 #2: BENCH_r01-r04
    all null while 36 successful on-chip stage records sat in the
    artifact).  Prefers ``full`` over ``small`` and a dtype matching
    ``--dtype``; returns ``None`` when no on-chip record exists.

    The stage log is append-only across rounds, so records older than
    ``--promote-max-age-h`` (default 48h ~ one build-round cadence)
    are ignored: a record from this or the previous session is
    promotable — its age rides along as ``provenance_recorded`` — but
    a tunnel dead for longer than a round yields an honest null
    instead of replaying an ancient number."""
    try:
        with open(_STAGES_PATH) as f:
            recs = [json.loads(line) for line in f if line.strip()]
    except (OSError, ValueError):
        return None

    def fresh(rec) -> bool:
        try:
            t = time.mktime(time.strptime(rec["t"][:19],
                                          "%Y-%m-%dT%H:%M:%S"))
        except (KeyError, ValueError):
            return False
        return (time.time() - t) <= args.promote_max_age_h * 3600.0

    for stage_name, metric in (("full", METRIC_FULL),
                               ("small", METRIC_SMALL)):
        cands = [r for r in recs
                 if r.get("ok") and r.get("stage") == stage_name
                 and r.get("result", {}).get("platform")
                 in ("tpu", "axon")
                 and r.get("result", {}).get("epoch_ms") is not None
                 and fresh(r)]
        if not cands:
            continue
        matched = [r for r in cands
                   if r["result"].get("dtype") == args.dtype]
        rec = (matched or cands)[-1]
        r = rec["result"]
        epoch_ms = r["epoch_ms"]
        line = {"metric": metric, "value": epoch_ms, "unit": "ms",
                "vs_baseline": 1.0, "stage": stage_name,
                "dtype": r.get("dtype"), "impl": r.get("impl"),
                "provenance": "in_round_stage",
                "provenance_recorded": rec.get("t"),
                "live_errors": errs, "stages": stage_summary}
        line.update(_baseline_compare_fields(
            _load_baselines().get(metric), r.get("platform"), epoch_ms))
        if line.get("baseline") == "recorded_now":
            # promotion never writes baselines; equal values just mean
            # the promoted record IS the recorded one
            line["baseline"] = "equals_baseline"
        return line
    return None


# ---------------------------------------------------------------- children

def _sync_fetch(x) -> None:
    """Fetch-based device barrier — the single shared implementation
    (block_until_ready is unreliable under the axon relay)."""
    from roc_tpu.utils.profiling import sync
    sync(x)


def child_probe(args) -> dict:
    _probe_note("start")
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    _probe_note("jax imported")
    # each heavy import gets its own note BEFORE the next phase label,
    # so a wedge anywhere leaves the artifact pointing at the true
    # culprit: "start" = jax import, "jax imported" = the roc_tpu
    # package, "claiming backend" = the claim (with heartbeats)
    from roc_tpu.obs.heartbeat import Heartbeat
    _probe_note("roc_tpu imported; claiming backend")
    t0 = time.time()
    # the historical silent hang: a held claim / wedged relay used to
    # time this child out with zero evidence — now it heartbeats
    with Heartbeat("claiming backend"):
        dev = jax.devices()[0]
    claim_s = time.time() - t0
    _probe_note(f"claimed in {claim_s:.1f}s; compiling matmul")
    t0 = time.time()
    with Heartbeat("probe matmul"):
        x = jnp.ones((1024, 1024))
        _sync_fetch(x @ x)
    _probe_note(f"matmul done in {time.time() - t0:.1f}s")
    return {"platform": dev.platform, "device_kind": dev.device_kind,
            "claim_s": round(claim_s, 2),
            "matmul_s": round(time.time() - t0, 2)}


def child_micro(args) -> dict:
    """Reduced-scale aggregation race; rows keyed by impl spec."""
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from roc_tpu.core.graph import random_csr
    from roc_tpu.core.partition import padded_edge_list
    from roc_tpu.ops.aggregate import aggregate, aggregate_ell

    V, E, F, iters = 50_000, 10_000_000, 256, 10
    dev = jax.devices()[0]
    g = random_csr(V, E, seed=0)
    feats_np = np.random.RandomState(0).rand(V + 1, F).astype(np.float32)
    feats_np[-1] = 0
    # honor --dtype: the micro race must measure the same feature
    # dtype the training step aggregates (mixed/bfloat16 -> bf16), and
    # the GB/s math must use that dtype's width
    from roc_tpu.train.trainer import resolve_dtypes
    dt, cdt = resolve_dtypes(args.dtype)
    feat_dtype = cdt if cdt is not None else dt
    feats = jnp.asarray(feats_np, dtype=feat_dtype)
    gb = E * F * jnp.dtype(feat_dtype).itemsize / 1e9

    def bench(fn):
        _sync_fetch(fn())
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            _sync_fetch(fn())
            times.append((time.perf_counter() - t0) * 1e3)
        return float(np.median(times))

    rows = {}
    from roc_tpu.core.ell import ell_from_graph
    table = ell_from_graph(g.row_ptr, g.col_idx, V)
    idx = tuple(jnp.asarray(a[0]) for a in table.idx)
    pos = jnp.asarray(table.row_pos[0])

    f_ell = jax.jit(lambda x: aggregate_ell(x, idx, pos, V))
    ms = bench(lambda: f_ell(feats))
    rows["ell"] = {"ms": round(ms, 2), "gbps": round(gb / ms * 1e3, 1)}

    try:
        from roc_tpu.core.ell import sectioned_from_graph
        from roc_tpu.ops.aggregate import aggregate_ell_sect
        sect = sectioned_from_graph(g.row_ptr, g.col_idx, V)
        sidx, sdst, meta = sect.as_jax()
        f_s = jax.jit(lambda x: aggregate_ell_sect(x, sidx, sdst, meta, V))
        ms = bench(lambda: f_s(feats))
        rows["sectioned"] = {"ms": round(ms, 2),
                             "gbps": round(gb / ms * 1e3, 1)}
    except Exception as e:  # noqa: BLE001 - report and continue
        rows["sectioned"] = {"error": _errstr(e)}

    try:
        from roc_tpu.kernels.ell_spmm import ell_aggregate_pallas
        f_pl = jax.jit(lambda x: ell_aggregate_pallas(x, idx, pos, V))
        ms = bench(lambda: f_pl(feats))
        rows["pallas"] = {"ms": round(ms, 2),
                          "gbps": round(gb / ms * 1e3, 1)}
    except Exception as e:  # noqa: BLE001 - report and continue
        rows["pallas"] = {"error": _errstr(e)}

    for impl, chunk in (("scan", 2048), ("blocked", 1024)):
        src, dst = padded_edge_list(g, multiple=chunk)
        srcj, dstj = jnp.asarray(src), jnp.asarray(dst)
        f = jax.jit(lambda x, i=impl, c=chunk:
                    aggregate(x, srcj, dstj, V, impl=i, chunk=c))
        try:
            ms = bench(lambda: f(feats))
            rows[f"{impl}:{chunk}"] = {"ms": round(ms, 2),
                                       "gbps": round(gb / ms * 1e3, 1)}
        except Exception as e:  # noqa: BLE001
            rows[f"{impl}:{chunk}"] = {"error": _errstr(e)}

    # micro_stream rows: the streamed-tier host->device pipeline, sync
    # vs prefetched staging (core/streaming.py StagingPool) — the
    # comm/compute overlap win shows up in BENCH_* next to the
    # aggregation race (benchmarks/micro_stream.py is the full probe)
    try:
        from roc_tpu.core.streaming import StreamedHead
        Vs, Fs, Hs, bs = 262_144, 128, 64, 32_768
        Xh = np.random.RandomState(1).rand(Vs, Fs).astype(np.float32)
        Wh = jnp.asarray(np.random.RandomState(2).rand(
            Fs, Hs).astype(np.float32))
        for depth, label in ((0, "stream:sync"), (1, "stream:prefetch")):
            head = StreamedHead(0.0, block_rows=bs, prefetch=depth)
            ms = bench(lambda: head.forward(Wh, Xh, None, False))
            st = head.pool.take_stats()  # summary computed on the pool
            rows[label] = {
                "ms": round(ms, 2), "prefetch": depth,
                "h2d_wait_p50_ms": st["wait_p50_ms"],
                "overlap_frac": st["overlap_frac"],
                "max_live_blocks": int(st["max_live"])}
    except Exception as e:  # noqa: BLE001 - report and continue
        rows["stream"] = {"error": _errstr(e)}

    # micro_partition rows: greedy sweep vs cost-balanced split of a
    # Zipf POWER-LAW graph (uniform degrees split near-identically
    # under both methods — the race needs hubs to say anything) — the
    # straggler shard's padded aggregation step under each split,
    # reusing the full probe's helpers so the two stay one convention
    # (benchmarks/micro_partition.py)
    try:
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "benchmarks"))
        import micro_partition as mp
        from roc_tpu.core.costmodel import PartitionCostModel
        from roc_tpu.core.graph import zipf_csr
        w = PartitionCostModel().search_weights()
        gz = zipf_csr(V, E // 4, a=1.2, seed=0)
        for method in ("greedy", "cost"):
            plan, row = mp.split_row(gz, 8, method, w, 8, 512)
            row["ms"] = round(mp.shard_step_ms(gz, plan, 128, iters),
                              2)
            rows[f"partition:{method}"] = row
    except Exception as e:  # noqa: BLE001 - report and continue
        rows["partition"] = {"error": _errstr(e)}

    # micro_mesh rows: the 1-D all-parts mesh vs the best (parts,
    # model) 2-D shape of the same device set — wide-model epoch +
    # at-rest state bytes per device (benchmarks/micro_mesh.py is the
    # full probe; the sentinel gates mesh_epoch_ratio over the BENCH
    # trajectory like overlap_frac).  Needs a factorable device count
    # with a model axis > 1 to say anything.
    try:
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "benchmarks"))
        import micro_mesh as mm
        from roc_tpu.parallel import candidate_mesh_shapes
        nd = len(jax.devices())
        if nd < 2 or len(candidate_mesh_shapes(nd)) < 2:
            rows["mesh"] = {"skipped": f"{nd} device(s)"}
        else:
            # the CPU rehearsal (no ICI, serial compiles for every
            # shape) runs a narrower race so the whole micro stage
            # fits its child budget; the chip gets the full width
            cpu = dev.platform == "cpu"
            nodes, dim, hid, eps = ((2048, 128, 128, 2) if cpu
                                    else (4096, 256, 256, 3))
            ds_m = mm.make_wide_dataset(nodes, 8, dim, 16)
            shapes, win = mm.mesh_race(ds_m, nd, hid, epochs=eps)
            rows["mesh:1d"] = dict(shapes[win["one_d"]],
                                   shape=win["one_d"])
            rows["mesh:2d"] = dict(
                shapes[win["best_2d"]], shape=win["best_2d"],
                mesh_epoch_ratio=win["mesh_epoch_ratio"],
                state_bytes_ratio=win["state_bytes_ratio"])
    except Exception as e:  # noqa: BLE001 - report and continue
        rows["mesh"] = {"error": _errstr(e)}
    return {"platform": dev.platform, "device_kind": dev.device_kind,
            "V": V, "E": E, "F": F, "iters": iters, "impls": rows}


def _gcn_ck_prefix(stage: str) -> str:
    """Rotation prefix for the checkpoint-aware GCN stages: one per
    stage name, under the artifacts dir (cleared by the parent at the
    START of each round so attempts within one round share it and
    rounds never contaminate each other)."""
    return os.path.join(_ART_DIR, f"bench_{stage}_ck")


def child_gcn(args, nodes: int, edges: int) -> dict:
    """The headline workload at the given scale.

    Checkpoint-aware (ROADMAP resilience follow-on): the child
    installs the PR-8 preemption guard and keeps a checkpoint rotation
    at ``_gcn_ck_prefix(stage)`` — the parent's SIGTERM on timeout
    lands an EMERGENCY checkpoint (exit 75), and the retry attempt
    resumes from it instead of re-training cold (the persistent
    compile cache already covers the recompile half).  The resumed
    epoch is recorded as ``resumed_from_epoch`` in the result and, via
    the progress file, in a failed attempt's partial."""
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from roc_tpu.core.graph import Dataset, random_csr
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer

    layers = [int(x) for x in args.layers.split("-")]
    t0 = time.time()
    from roc_tpu.obs.heartbeat import Heartbeat
    with Heartbeat("claiming backend"):
        dev = jax.devices()[0]
    print(f"# device: {dev.platform} {dev.device_kind} "
          f"(claim {time.time() - t0:.1f}s)", file=sys.stderr)
    if args.impl == "auto":
        # resolve here so the recorded baseline names the kernel that
        # actually ran, not the CLI alias.  AFTER the claim above:
        # sectioned_bounds consults the backend's device_kind, and the
        # backend claim must stay the explicitly timed step (wedge
        # diagnosis reads that number).  num_edges arms the flat_sum
        # compile-wall route past the sectioned window (core/ell.py
        # FLAT_SUM_MIN_EDGES).
        from roc_tpu.core.ell import resolve_auto_impl
        args.impl = resolve_auto_impl(nodes, num_edges=edges)

    t0 = time.time()
    graph = random_csr(nodes, edges, seed=0)
    rng = np.random.RandomState(1)
    feats = rng.rand(nodes, layers[0]).astype(np.float32)
    labels = rng.randint(0, layers[-1], size=nodes).astype(np.int32)
    # Reddit-like split: 66% train / 10% val / 24% test
    mask = rng.choice([1, 2, 3], size=nodes,
                      p=[0.66, 0.10, 0.24]).astype(np.int32)
    ds = Dataset(graph=graph, features=feats, labels=labels, mask=mask,
                 num_classes=layers[-1], name="reddit-synth")
    print(f"# data gen: {time.time()-t0:.1f}s V={nodes} "
          f"E={graph.num_edges}", file=sys.stderr)

    # "mixed" = fp32 master params + bf16 compute (halves aggregation
    # HBM traffic); "bfloat16" = everything bf16; resolve_dtypes is the
    # shared CLI/bench mapping
    from roc_tpu.train.trainer import resolve_dtypes
    dtype, compute_dtype = resolve_dtypes(args.dtype)
    model = build_gcn(layers, dropout_rate=0.5)
    # eval_every larger than any epoch count: timed epochs are pure
    # train steps, matching the reference's epoch cost (inference runs
    # only every 5th epoch there, gnn.cc:107-110, and is excluded here)
    cfg = TrainConfig(learning_rate=0.01, weight_decay=1e-4,
                      decay_rate=0.97, decay_steps=100,
                      aggr_impl=args.impl, chunk=args.chunk,
                      dtype=dtype, compute_dtype=compute_dtype,
                      verbose=False, eval_every=1 << 30,
                      symmetric=True)
    t0 = time.time()
    trainer = Trainer(model, ds, cfg)
    # resilience wiring: guard + rotation BEFORE any long phase, so
    # the parent's timeout SIGTERM is answered with an emergency
    # checkpoint instead of lost work
    from roc_tpu.resilience import preempt
    from roc_tpu.resilience.recovery import CheckpointRotation
    preempt.install()
    # async saves (ISSUE 15): the rotation's checkpoints run CRC +
    # write + commit on the saver thread; only the finite guard +
    # host snapshot touch the timed path.  Emergency saves flush.
    rotation = CheckpointRotation(_gcn_ck_prefix(args.stage), keep=2,
                                  async_save=True)
    resumed_from = rotation.restore_latest(trainer,
                                           only_if_ahead=True)
    if resumed_from is not None:
        _probe_note(f"resumed_from_epoch={resumed_from}")
        print(f"# resumed from emergency checkpoint (epoch "
              f"{resumed_from}) — warm retry, not a cold rerun",
              file=sys.stderr)
    # pre-warm BEFORE the timed phase: AOT-compile the trainer's whole
    # program set against the persistent cache (run_child enabled it
    # at min_compile_secs=0) and RECORD warm-vs-cold — the compile
    # wall becomes a tracked metric instead of a blank timeout (the
    # r01-r05 probe deaths were all first-compile stalls)
    from roc_tpu.utils.prewarm import warm_trainer
    try:
        warm = warm_trainer(trainer, name=f"bench:{nodes}")
        print(f"# prewarm: {warm.get('compile_warm_hits')} warm / "
              f"{warm.get('compile_cold')} cold in "
              f"{warm.get('prewarm_s')}s", file=sys.stderr)
    except Exception as e:  # noqa: BLE001 - warming is best-effort
        warm = {"error": _errstr(e)}
        print(f"# prewarm failed (continuing cold): {warm['error']}",
              file=sys.stderr)
    from roc_tpu.resilience.preempt import (Preempted,
                                            RESTARTABLE_EXIT_CODE)
    try:
        trainer.train(epochs=2)  # compile lap (barriered) + 1
        trainer.sync()
        compile_s = time.time() - t0
        print(f"# compile+warmup: {compile_s:.1f}s", file=sys.stderr)
        # post-compile checkpoint: even a SIGKILL mid-timed-loop
        # resumes the retry past the compile wall
        rotation.save(trainer)
        _probe_note(f"warmup done; checkpoint at epoch "
                    f"{trainer.epoch}")

        times = []
        for _ in range(args.epochs):
            t0 = time.time()
            trainer.train(epochs=1)
            trainer.sync()
            times.append((time.time() - t0) * 1000.0)
        epoch_ms = float(np.median(times))
        print(f"# epoch times (ms): {[round(t, 1) for t in times]}",
              file=sys.stderr)
        m = trainer.evaluate()
        # checkpoint cost row (ISSUE 15): the synchronous save's full
        # wall vs the async save's step-path blocked time, on the
        # SAME trainer state — the headline's ckpt_save_ms /
        # ckpt_block_ms pair, sentinel-gated lower-better
        import shutil
        from roc_tpu.utils.checkpoint import checkpoint_trainer
        sync_dir = _gcn_ck_prefix(args.stage) + ".sync_probe"
        t0 = time.perf_counter()
        checkpoint_trainer(trainer, sync_dir)
        ckpt_sync_ms = (time.perf_counter() - t0) * 1e3
        shutil.rmtree(sync_dir, ignore_errors=True)
        t0 = time.perf_counter()
        rotation.save(trainer)
        ckpt_block_ms = (time.perf_counter() - t0) * 1e3
        rotation.flush()
        saves = rotation.save_stats().get("saves") or []
        ckpt_save_ms = saves[-1]["save_ms"] if saves else None
        print(f"# checkpoint: sync {ckpt_sync_ms:.1f} ms wall, async "
              f"blocks step path {ckpt_block_ms:.1f} ms "
              f"(background save "
              f"{ckpt_save_ms if ckpt_save_ms is not None else '?'} "
              f"ms)", file=sys.stderr)
    except Preempted:
        # the parent's timeout SIGTERM (or a real preemption): persist
        # the in-flight progress through the rotation — FLUSHED, so
        # 'emergency checkpoint' means committed on disk — and exit
        # restartable; the NEXT attempt resumes from here
        path = rotation.save(trainer)
        rotation.flush()
        _probe_note(f"preempted; emergency checkpoint at epoch "
                    f"{trainer.epoch}")
        print(f"# preempted: emergency checkpoint "
              f"{os.path.basename(path)} (epoch {trainer.epoch}) — "
              f"exiting restartable", file=sys.stderr)
        raise SystemExit(RESTARTABLE_EXIT_CODE)
    # the synthetic graph carries RANDOM labels: these accuracies only
    # prove the step runs end-to-end; they are NOT a quality signal
    # (real-data accuracy gates live in tests/, cf. VERDICT r3 weak #4)
    print(f"# end-to-end check (random labels, not a quality signal): "
          f"train_acc={m['train_acc']:.3f} test_acc={m['test_acc']:.3f}",
          file=sys.stderr)
    return {"platform": dev.platform, "device_kind": dev.device_kind,
            "V": nodes, "E": int(graph.num_edges),
            "layers": args.layers, "impl": args.impl,
            "dtype": args.dtype, "epochs_timed": args.epochs,
            # compile_s includes persistent-cache hits (near-zero on
            # repeat runs) — epoch_ms is the comparable metric.
            # compile_warm_hits/compile_cold track the compile wall
            # itself: a repeat run should be all-warm, and a cold
            # count on an unchanged config means the program set or
            # the cache key drifted (analysis/programspace.py).
            "compile_s": round(compile_s, 1),
            "compile_warm_hits": warm.get("compile_warm_hits"),
            "compile_cold": warm.get("compile_cold"),
            "prewarm_s": warm.get("prewarm_s"),
            "epoch_ms": round(epoch_ms, 2),
            "epoch_ms_all": [round(t, 1) for t in times],
            # d2h persistence cost (checkpoint v3): full async save
            # wall, step-path blocked time, and the sync reference —
            # the step-path number is what async saving buys back
            "ckpt_save_ms": (round(ckpt_save_ms, 2)
                             if ckpt_save_ms is not None else None),
            "ckpt_block_ms": round(ckpt_block_ms, 2),
            "ckpt_sync_ms": round(ckpt_sync_ms, 2),
            "resumed_from_epoch": resumed_from,
            "labels": "synthetic_random",
            "random_label_train_acc": round(float(m["train_acc"]), 4),
            "random_label_test_acc": round(float(m["test_acc"]), 4)}


def child_serve(args) -> dict:
    """Serving-tier load generation (benchmarks/micro_serve.py): both
    backends exported through the real artifact path, a cold-loaded
    server driven closed-loop and open-loop Poisson; the headline line
    picks up the precomputed backend's p50/p99/QPS
    (``serve_p50_ms``/``serve_p99_ms``/``serve_qps``), gated by the
    sentinel like epoch time.  The kill-a-replica router drill
    (micro_serve.run_router_drill — 2 CPU replicas, replica 1
    SIGKILLed mid-load) contributes the availability columns
    (``serve_shed_rate``/``serve_error_rate``/``serve_availability``)
    the sentinel's availability checks gate.  The ``precomputed_q8``
    row (PR 19) re-exports the precomputed backend at int8 and feeds
    the ``serve_table_bytes``/``serve_quant_drift`` columns — the
    artifact's table bytes and the export drift gate's relative
    max |Δlogit|."""
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    sys.path.insert(0, os.path.join(_HERE, "benchmarks"))
    import micro_serve as ms
    import tempfile
    ds, model, cfg = ms.build_rig(20_000, 8, 128, 16, 2)
    rows = {}
    with tempfile.TemporaryDirectory(prefix="roc_serve_") as art:
        for backend in ("precomputed", "full"):
            from roc_tpu.models.builder import Model
            rows[backend] = ms.run_backend(
                backend, ds, Model.from_spec(model.to_spec()), cfg,
                queries=200, batch=4, rate="auto", art_root=art)
        # the quantized-serving A/B (PR 19): the precomputed backend
        # re-exported at int8 — table bytes + the export drift gate's
        # measurements become the serve_table_bytes /
        # serve_quant_drift headline columns the sentinel gates
        try:
            from roc_tpu.models.builder import Model
            rows["precomputed_q8"] = ms.run_backend(
                "precomputed", ds, Model.from_spec(model.to_spec()),
                cfg, queries=200, batch=4, rate="auto", art_root=art,
                quant="int8")
        except Exception as e:  # noqa: BLE001 - latency rows survive
            rows["precomputed_q8"] = {"error": _errstr(e)}
        try:
            from roc_tpu.models.builder import Model
            drill = ms.run_router_drill(
                ds, Model.from_spec(model.to_spec()), cfg, art,
                queries=120, batch=4)
        except Exception as e:  # noqa: BLE001 - latency rows survive
            drill = {"error": _errstr(e)}
        # the quiet SLO smoke (PR 17): 2-replica Router with declared
        # objectives, 100-query load-gen, health() must be green —
        # the serve_slo_ok headline column the sentinel gates
        try:
            from roc_tpu.models.builder import Model
            slo_smoke = ms.run_slo_smoke(
                ds, Model.from_spec(model.to_spec()), cfg, art,
                queries=100, batch=4)
        except Exception as e:  # noqa: BLE001 - latency rows survive
            slo_smoke = {"error": _errstr(e)}
        # the sharded-capacity row (PR 20): total table above one
        # replica's byte cap, slices gathered across the fleet at
        # availability 1.0 bit-exact — feeds the
        # serve_shard_table_bytes / serve_gather_p50_ms columns
        try:
            from roc_tpu.models.builder import Model
            shard_cap = ms.run_shard_capacity(
                ds, Model.from_spec(model.to_spec()), cfg, art,
                queries=60, batch=4)
        except Exception as e:  # noqa: BLE001 - latency rows survive
            shard_cap = {"error": _errstr(e)}
    out = {"platform": dev.platform, "device_kind": dev.device_kind,
           "V": int(ds.graph.num_nodes), "E": int(ds.graph.num_edges),
           "queries": 200, "batch": 4, "backends": rows,
           "router_drill": drill, "slo_smoke": slo_smoke,
           "shard_capacity": shard_cap}
    pre, full = rows.get("precomputed"), rows.get("full")
    if pre and full:
        out["speedup_p50"] = round(
            full["closed"]["p50_ms"]
            / max(pre["closed"]["p50_ms"], 1e-9), 1)
    return out


def run_child(args) -> None:
    # persistent XLA cache: repeat runs (driver retries, staged
    # protocol, round-over-round) skip the 1-2 min full-scale compile
    # — directly shrinks the timeout risk the staging exists for.
    # min_compile_secs=0: prewarm is driving (child_gcn warms its
    # whole program set before the timed phase), so even sub-second
    # programs must persist — the 1.0 s default silently skipped the
    # small per-block streamed-head programs.
    from roc_tpu.utils.compile_cache import enable_compile_cache
    from roc_tpu.obs.events import install_excepthook
    install_excepthook()   # crash flight recorder for dead children
    cache_dir = enable_compile_cache(min_compile_secs=0.0)
    if args.stage == "probe":
        # warm-start evidence in the progress artifact: repeat probes
        # hit the persistent cache, so a slow matmul phase on attempt
        # N>1 means tunnel weather, not compile cost
        _probe_note(f"compile cache ready at "
                    f"{cache_dir or '(disabled)'}")
        out = child_probe(args)
    elif args.stage == "micro":
        out = child_micro(args)
    elif args.stage == "small":
        out = child_gcn(args, 2048, 32768)
    elif args.stage == "full":
        out = child_gcn(args, args.nodes, args.edges)
    elif args.stage == "serve":
        out = child_serve(args)
    else:
        raise SystemExit(f"unknown stage {args.stage!r}")
    print(json.dumps(out))


# ---------------------------------------------------------------- parent

# seconds granted to a SIGTERM'd child to unwind its TPU claim; the
# parent budgets this INSIDE the deadline (timeout + grace + finalize
# must fit in what remains, or the final JSON line could print after
# the driver's own timeout already fired)
_TERM_GRACE = 45.0


# ------------------------------------------------ stderr dedupe filter
#
# The r05 driver tail was 5x the same "Platform 'axon' is experimental"
# jax warning — one per probe retry — drowning the useful stall lines.
# Stage-child stderr is forwarded through this filter: third-party
# lines that normalize identically (digits collapsed, so re-dated
# warnings match) print once, repeats are counted and summarized.  Our
# own "# ..." diagnostics pass through untouched — heartbeats and
# retry notes are the evidence the tail exists to preserve.

_STDERR_SEEN: dict = {}

# dedupe-eligible shapes: python logging / absl prefixes — the spam
# class the r05 tail drowned in.  Deliberately NOT "everything
# non-'#'": tracebacks and error messages must never dedupe (two
# different crashes can share frame lines once digits normalize, and
# a half-suppressed traceback is worse than a repeated one).
_DEDUP_ELIGIBLE = re.compile(
    r"^\s*(WARNING|ERROR|INFO|DEBUG|CRITICAL)[:\s]|^[WEIF]\d{4}\s")


def _dedup_key(line: str):
    """Normalization key for dedupe-eligible stderr lines (digits
    collapsed so re-dated repeats of one warning match); None means
    always forward (everything that is not a logging-prefixed line —
    this repo's own '# ' diagnostics, tracebacks, error text)."""
    s = line.strip()
    if not s or not _DEDUP_ELIGIBLE.match(s):
        return None
    return re.sub(r"[0-9.]+", "N", s)


def _forward_stderr(pipe, counts: dict) -> None:
    """Reader-thread body: forward child stderr line by line, deduping
    repeated identical (normalized) third-party lines across ALL
    stage children of this parent run."""
    try:
        for line in iter(pipe.readline, ""):
            line = line.rstrip("\n")
            key = _dedup_key(line)
            if key is None:
                print(line, file=sys.stderr)
                continue
            n = _STDERR_SEEN.get(key, 0)
            _STDERR_SEEN[key] = n + 1
            if n == 0:
                print(line, file=sys.stderr)
            else:
                counts["suppressed"] = counts.get("suppressed", 0) + 1
                if n == 1:
                    print(f"# [stderr dedup] repeat suppressed from "
                          f"here on: {line.strip()[:110]}",
                          file=sys.stderr)
    except (OSError, ValueError):
        pass  # child torn down mid-line
    finally:
        try:
            pipe.close()
        except OSError:
            pass


def _sentinel_verdict(epoch_ms, dtype=None, compile_s=None,
                      stage=None):
    """Regression-sentinel verdict for a headline epoch value vs the
    checked-in BENCH_r*.json round history (roc_tpu/obs/sentinel.py —
    stdlib-only, so the jax-free parent can call it).  Best-effort:
    the headline must never be blocked by a broken sentinel."""
    try:
        _light_obs_imports()
        from roc_tpu.obs.sentinel import bench_verdict
        return bench_verdict(epoch_ms, dtype=dtype,
                             compile_s=compile_s, bench_dir=_HERE,
                             stage=stage)
    except Exception as e:  # noqa: BLE001 - verdict is best-effort
        return {"verdict": "unavailable", "error": _errstr(e)}


def _run_stage(name: str, timeout: float, argv,
               grace: float = _TERM_GRACE,
               partial_extra: dict = None) -> dict:
    """Run one stage child under ``timeout``; returns its record
    (``ok`` key tells success).  Persists the attempt immediately.
    ``partial_extra`` merges into a failed probe's partial result
    (the retry loop records the attempt index + the backoff that
    preceded it, so the artifact shows the retry cadence).

    The wait runs under a stall heartbeat (roc_tpu/obs): a wedged
    stage emits "still waiting in bench:<stage>" events to stderr and
    the events artifact BEFORE its timeout, so the round-5 failure
    mode — every stage timing out with zero evidence — cannot recur."""
    _light_obs_imports()
    from roc_tpu.obs.heartbeat import Heartbeat, heartbeat_interval
    t0 = time.time()
    rec = {"stage": name, "t": _now_iso(), "timeout_s": round(timeout, 0)}
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--stage", name] + argv,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    # child stderr rides through the dedupe filter on its own reader
    # thread; detach the pipe from proc so communicate() below never
    # races the reader for it
    import threading
    dd_counts: dict = {}
    stderr_pipe, proc.stderr = proc.stderr, None
    reader = threading.Thread(target=_forward_stderr,
                              args=(stderr_pipe, dd_counts),
                              name=f"stderr:{name}", daemon=True)
    reader.start()
    # deadline_s=0: the stall deadline (ROC_TPU_STALL_TIMEOUT_S) is
    # for the CHILD's hanging regions (first compile, backend claim)
    # — the parent already bounds this wait with its own stage
    # timeout, and an env-armed deadline here would cut communicate()
    # short and mis-classify a slow-but-alive stage as a stall
    hb = Heartbeat(f"bench:{name}", heartbeat_interval(),
                   deadline_s=0, timeout_s=round(timeout, 0))
    try:
        with hb:
            out, _ = proc.communicate(timeout=timeout)
        if proc.returncode == 0:
            for line in reversed(out.splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    rec.update(ok=True, result=json.loads(line))
                    break
            else:
                rec.update(ok=False,
                           error="child exited 0 without a JSON line")
        else:
            rec.update(ok=False, error=f"child rc={proc.returncode}")
    except subprocess.TimeoutExpired:
        # SIGTERM only: SIGKILL on a TPU-claim holder can wedge the
        # tunnel relay for every subsequent process
        proc.terminate()
        try:
            proc.communicate(timeout=grace)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
        rec.update(ok=False, error=f"timeout after {timeout:.0f}s")
    reader.join(timeout=5.0)
    # a reader wedged past the join deadline (stuck pipe read) is
    # still mutating dd_counts — don't race it for the summary, and
    # record the leak instead of silently dropping it (roc-lint
    # level six's thread-no-shutdown-path contract: the join above IS
    # the reader's bounded stop path, so a miss is reportable)
    if reader.is_alive():
        rec["stderr_reader_leaked"] = True
    elif dd_counts.get("suppressed"):
        rec["stderr_suppressed"] = dd_counts["suppressed"]
    rec["elapsed_s"] = round(time.time() - t0, 1)
    if hb.fired:
        rec["heartbeats"] = hb.fired
    if name in ("probe", "small", "full") and not rec.get("ok"):
        # where the attempt died (claim-wait vs matmul vs epoch N) —
        # wedge vs slow is diagnosable from the artifact alone, and
        # the heartbeat-dated partial result below is what the
        # parent's same-phase retry abort reads (a timed-out probe
        # must never be a silent null: r04/r05 burned the whole
        # deadline retrying into the identical wedge).  GCN stages
        # also record the checkpoint-resume evidence: a retry that
        # resumed from the previous attempt's emergency checkpoint
        # carries resumed_from_epoch (ROADMAP checkpoint-aware probe)
        prog = _read_probe_progress()
        rec["progress"] = prog
        rec["partial"] = {"t": _now_iso(), "last_phase": _probe_phase(prog),
                          "heartbeats": hb.fired,
                          "elapsed_s": rec["elapsed_s"],
                          **(partial_extra or {})}
        resumed = _progress_resumed_epoch(prog)
        if resumed is not None:
            rec["partial"]["resumed_from_epoch"] = resumed
    _append_stage(rec)
    from roc_tpu.obs.events import emit
    emit("bench", f"stage {name}: "
         f"{'ok' if rec.get('ok') else rec.get('error')} "
         f"({rec['elapsed_s']}s)", stage=name,
         ok=bool(rec.get("ok")), elapsed_s=rec["elapsed_s"])
    return rec


def _baseline_entry(result: dict, extra_keys=("V", "E", "layers", "impl",
                                              "dtype")) -> dict:
    entry = {"recorded": _now_iso(),
             "platform": result.get("platform"),
             "device_kind": result.get("device_kind"),
             "provenance": "bench.py staged run"}
    for k in extra_keys:
        if k in result:
            entry[k] = result[k]
    return entry


def parent(args, argv) -> int:
    t_start = time.time()
    remaining = lambda: args.deadline - (time.time() - t_start)
    # structured events ride next to bench_stages.jsonl; the env var
    # makes every stage CHILD (trainer manifest/compile events, claim
    # heartbeats) append to the same artifact
    events_path = (os.environ.get("ROC_TPU_EVENTS")
                   or os.path.join(_ART_DIR, "events.jsonl"))
    os.environ["ROC_TPU_EVENTS"] = events_path
    _light_obs_imports()
    from roc_tpu.obs.events import configure
    configure(jsonl_path=events_path)
    # Recording: non-fp32 dtypes ALSO record under dtype-suffixed
    # metric names so per-config provenance never overwrites the fp32
    # record.  The HEADLINE line, however, always uses the unsuffixed
    # metric and compares against the project's recorded baseline (the
    # first-ever TPU measurement, fp32 ell) with explicit dtype/impl
    # fields on both sides — the production config is mixed precision
    # and its speedup over the recorded baseline is the honest summary.
    suffix = "" if args.dtype == "float32" else f"_{args.dtype}"
    metric_full = METRIC_FULL + suffix
    metric_small = METRIC_SMALL + suffix
    metric_micro = METRIC_MICRO + suffix
    metric_serve = METRIC_SERVE + suffix
    wanted = [s.strip() for s in args.stages.split(",") if s.strip()]
    if args.small:
        wanted = ["probe", "small"]
    stage_cfg = {n: (t, m) for n, t, m in STAGES}
    probe_to = os.environ.get("ROC_TPU_BENCH_PROBE_TIMEOUT")
    if probe_to:
        try:
            t = float(probe_to)
            stage_cfg["probe"] = (t, min(stage_cfg["probe"][1], t))
        except ValueError:
            pass
    unknown = [n for n in wanted if n not in stage_cfg]
    if unknown:
        # keep the always-one-JSON-line contract even for bad input
        print(json.dumps({"metric": METRIC_FULL, "value": None,
                          "unit": "ms", "vs_baseline": None,
                          "error": f"unknown stages {unknown}; valid: "
                                   f"{[n for n, _, _ in STAGES]}"}))
        return 2
    results: dict = {}

    if not args.cpu and not os.environ.get(
            "ROC_TPU_BENCH_NO_PREFLIGHT"):
        # programspace preflight: refuse to burn chip deadline on a
        # config whose program set GREW since the cached warm state —
        # every new program is a cold first compile on the chip, the
        # exact blank-timeout class the staged protocol exists to
        # avoid.  A dated programspace event + stage record replace
        # the old silent death; re-running `python -m roc_tpu.prewarm`
        # (which refreshes the warm state) clears the refusal.
        grown = _programspace_preflight()
        if grown:
            msg = (f"program set grew since cached warm state: "
                   f"{grown} — run `python -m roc_tpu.prewarm` "
                   f"before burning chip deadline")
            from roc_tpu.obs.events import emit as _emit
            _emit("programspace", msg, grown=grown,
                  preflight="refused")
            _append_stage({"stage": "programspace_preflight",
                           "t": _now_iso(), "ok": False,
                           "grown": grown, "error": msg})
            print(f"# {msg}", file=sys.stderr)
            print(json.dumps({
                "metric": METRIC_FULL, "value": None, "unit": "ms",
                "vs_baseline": None, "stage": None,
                "error": {"programspace_preflight": msg}}))
            return 1
        if grown is not None:
            _append_stage({"stage": "programspace_preflight",
                           "t": _now_iso(), "ok": True, "grown": {}})

    if not args.cpu:
        # the probe must never queue behind this session's own corpses
        # (a stale tpu_watch loop re-probing every ~4 min starved the
        # r03 bench outright)
        reaped = _reap_stale_tpu_processes()
        if reaped:
            _append_stage({"stage": "reap", "t": _now_iso(),
                           "reaped": reaped})
            print(f"# reaped {len(reaped)} stale TPU process(es): "
                  f"{[v['pid'] for v in reaped]}", file=sys.stderr)
        # relay-health pre-check: a dead relay and a held claim look
        # identical from inside the probe ('claiming backend' hang);
        # this tells them apart in the artifact
        health = _relay_health()
        _append_stage({"stage": "relay_check", "t": _now_iso(),
                       **health})
        print(f"# relay tcp 127.0.0.1:{health['port']}: "
              f"{health['state']}", file=sys.stderr)

    for name in wanted:
        timeout, min_budget = stage_cfg[name]
        if name != "probe" and "probe" in wanted and \
                not results.get("probe", {}).get("ok"):
            results[name] = {"ok": False, "error": "probe failed"}
            continue
        # child timeout + SIGTERM grace + finalize margin must all fit
        # in the remaining deadline
        budget = remaining() - 20.0 - _TERM_GRACE
        if budget < min_budget:
            results[name] = {"ok": False,
                             "error": f"skipped: {budget:.0f}s left "
                                      f"< min {min_budget:.0f}s"}
            _append_stage({"stage": name, "t": _now_iso(),
                           **results[name]})
            print(f"# stage {name}: {results[name]['error']}",
                  file=sys.stderr)
            continue
        eff_timeout = min(timeout, budget)
        if name == "probe":
            # the claim can be busy or the relay wedged for tens of
            # minutes: back attempts off EXPONENTIALLY (with jitter)
            # up to the _PROBE_INTERVAL cap, spread across the WHOLE
            # deadline, stopping only when one more probe plus the
            # cheapest measurement stage could no longer fit.  The
            # r04/r05 deadline burn was immediate identical retries —
            # the same-phase abort below caps the COUNT, the backoff
            # caps the CADENCE; each attempt's partial records the
            # spacing that preceded it.
            last_phase = None
            prev_wait = 0.0
            for attempt in range(args.probe_retries + 1):
                t_attempt = time.time()
                try:  # fresh progress file per attempt
                    os.unlink(_probe_progress_path())
                except OSError:
                    pass
                rec = _run_stage(
                    name,
                    min(eff_timeout,
                        remaining() - 20 - _TERM_GRACE), argv,
                    partial_extra={"attempt": attempt + 1,
                                   "backoff_s": round(prev_wait, 1)})
                if rec.get("ok") or attempt == args.probe_retries:
                    break
                # same-phase abort: two consecutive attempts that died
                # at the identical (normalized) phase with zero new
                # progress mean the tunnel is wedged on the ~30 min
                # scale — further 150 s retries only burn the deadline
                # that the in-round promotion path and any remaining
                # stages could still use (the r04/r05 failure shape:
                # five identical "timeout after 150s" probes, nothing
                # else ever ran)
                phase = (rec.get("partial") or {}).get("last_phase")
                if phase is not None and phase == last_phase:
                    print(f"# probe died at the same phase twice "
                          f"({phase}) — aborting retries to preserve "
                          f"the deadline", file=sys.stderr)
                    _append_stage({"stage": "probe_abort",
                                   "t": _now_iso(), "phase": phase,
                                   "attempts": attempt + 1})
                    break
                last_phase = phase
                # one more cycle = probe timeout + its grace + the
                # cheapest still-wanted measurement stage's min budget
                # + finalize margin
                later_mins = [stage_cfg[n][1] for n in wanted
                              if n != "probe"]
                needed = (stage_cfg["probe"][0] + _TERM_GRACE
                          + (min(later_mins) if later_mins else 0) + 60)
                if remaining() < needed:
                    break
                # exponential backoff: attempt n targets
                # interval/4 * 2^n seconds between attempt STARTS,
                # capped at the interval (the spread-across-deadline
                # bound), jittered +/-25% so parallel rounds never
                # re-bunch their probes on the wedged relay
                import random
                target = min(_probe_interval(),
                             _probe_interval() / 4.0 * (2 ** attempt))
                target *= random.uniform(0.75, 1.25)
                wait = max(0.0, target - (time.time() - t_attempt))
                wait = min(wait, max(remaining() - needed, 0.0))
                prev_wait = wait
                if wait > 0:
                    print(f"# probe retry in {wait:.0f}s (backoff "
                          f"attempt {attempt + 1}, "
                          f"{remaining():.0f}s of deadline left)",
                          file=sys.stderr)
                    time.sleep(wait)
        else:
            # measurement stages get ONE retry — the single-claim
            # tunnel can transiently fail any fresh child, not just the
            # probe (observed: a full-stage rc=1 with ~690s left), but
            # a deterministic failure must not starve later stages.
            # GCN stages are checkpoint-aware: attempt 0 starts from a
            # cleared rotation; a timed-out attempt's emergency
            # checkpoint lets attempt 1 RESUME instead of re-training
            # cold (resumed_from_epoch lands in the result/partial)
            if name in ("small", "full"):
                _clear_gcn_checkpoints(name)
            for attempt in range(2):
                try:  # fresh progress markers per attempt
                    os.unlink(_probe_progress_path())
                except OSError:
                    pass
                rec = _run_stage(name, eff_timeout, argv)
                budget = remaining() - 20.0 - _TERM_GRACE
                if rec.get("ok") or budget < min_budget:
                    break
                if attempt == 0:
                    print(f"# {name} retry in 30s ({budget:.0f}s left)",
                          file=sys.stderr)
                    time.sleep(30)
                    eff_timeout = min(
                        timeout, remaining() - 20.0 - _TERM_GRACE)
        results[name] = rec

        # persist measurements as baselines the moment they exist;
        # each stage reports its own platform (a probe-less --stages
        # run must still record TPU results)
        if rec.get("ok") and not args.cpu and \
                rec["result"].get("platform") in ("tpu", "axon"):
            r = rec["result"]
            if name == "micro":
                entry = _baseline_entry(r, extra_keys=("V", "E", "F"))
                entry["impls"] = r["impls"]
                _record_baseline(metric_micro, entry)
                if metric_micro != METRIC_MICRO:
                    _record_baseline(METRIC_MICRO, entry)
            elif name == "serve":
                entry = _baseline_entry(
                    r, extra_keys=("V", "E", "queries", "batch"))
                entry["backends"] = r["backends"]
                entry["speedup_p50"] = r.get("speedup_p50")
                _record_baseline(metric_serve, entry)
                if metric_serve != METRIC_SERVE:
                    _record_baseline(METRIC_SERVE, entry)
            elif name in ("small", "full"):
                metric = metric_small if name == "small" else metric_full
                entry = _baseline_entry(r)
                entry["epoch_ms"] = r["epoch_ms"]
                entry["compile_s"] = r.get("compile_s")
                _record_baseline(metric, entry)
                # the unsuffixed metric is the project's headline
                # record: the first-ever TPU measurement claims it
                # (whatever its dtype — the entry says which)
                base = METRIC_SMALL if name == "small" else METRIC_FULL
                if base != metric:
                    _record_baseline(base, entry)

    # headline line: the furthest completed GCN stage, under the
    # UNSUFFIXED metric name, compared against the project's recorded
    # baseline (first-ever TPU measurement) with dtype/impl fields on
    # both sides so a precision-policy speedup is never a hidden claim
    stage_summary = {n: (results[n].get("result")
                         if results[n].get("ok")
                         else {"error": results[n].get("error")})
                     for n in results}
    # serving-tier headline fields: the precomputed backend's
    # closed-loop p50/p99 + QPS ride every headline line (and the
    # sentinel's trajectory gate reads them from the BENCH history
    # exactly like epoch time — obs/sentinel.py load_bench_round)
    serve_fields = {}
    sv = results.get("serve")
    if sv and sv.get("ok"):
        pre = (sv["result"].get("backends") or {}).get("precomputed")
        closed = (pre or {}).get("closed") or {}
        if closed.get("p50_ms") is not None:
            serve_fields = {"serve_p50_ms": closed.get("p50_ms"),
                            "serve_p99_ms": closed.get("p99_ms"),
                            "serve_qps": closed.get("qps"),
                            # PR 17: server-side latency decomposition
                            # (queue delay vs device wall)
                            "serve_queue_p50_ms":
                                closed.get("queue_p50_ms"),
                            "serve_device_p50_ms":
                                closed.get("device_p50_ms"),
                            "serve_speedup_p50":
                                sv["result"].get("speedup_p50")}
        # the SLO smoke verdict: 1.0 = Router.health() green on a
        # quiet 100-query load-gen (sentinel-gated higher-better)
        smoke = sv["result"].get("slo_smoke") or {}
        if smoke.get("ok") is not None:
            serve_fields["serve_slo_ok"] = (1.0 if smoke.get("ok")
                                            else 0.0)
        # quantized serving (PR 19): the int8 A/B row's artifact
        # table bytes + the export drift gate's relative max |Δlogit|
        # — the serve_table_bytes (lower-better: a regression means
        # the shrink was lost) and serve_quant_drift (gate metric)
        # sentinel columns, mined exactly like the latency pair
        q8 = (sv["result"].get("backends") or {}).get(
            "precomputed_q8") or {}
        if q8.get("table_bytes") is not None:
            serve_fields["serve_table_bytes"] = q8.get("table_bytes")
            serve_fields["serve_quant_drift"] = q8.get("quant_drift")
            serve_fields["serve_table_shrink"] = q8.get("table_shrink")
            serve_fields["serve_p50_int8_ms"] = (
                q8.get("closed") or {}).get("p50_ms")
        # availability columns from the kill-a-replica router drill —
        # the sentinel gates these over the BENCH trajectory exactly
        # like serve_p50_ms (obs/sentinel.py serve_shed_rate /
        # serve_error_rate lower-better, serve_availability
        # higher-better)
        drill = sv["result"].get("router_drill") or {}
        if drill.get("availability") is not None:
            serve_fields.update(
                serve_shed_rate=drill.get("shed_rate"),
                serve_error_rate=drill.get("error_rate"),
                serve_availability=drill.get("availability"),
                serve_failover=drill.get("failover"),
                serve_wrong=drill.get("wrong"))
        # sharded serving (PR 20): per-replica slice bytes (lower-
        # better: a regression means the slicing stopped shrinking
        # the per-replica footprint) + the cross-shard gather leg's
        # p50 (lower-better: the request-path cost of not holding
        # the whole table), mined from the capacity row
        cap = sv["result"].get("shard_capacity") or {}
        if cap.get("serve_shard_table_bytes") is not None:
            serve_fields["serve_shard_table_bytes"] = cap.get(
                "serve_shard_table_bytes")
            serve_fields["serve_gather_p50_ms"] = cap.get(
                "serve_gather_p50_ms")
    for name, metric in (("full", METRIC_FULL), ("small", METRIC_SMALL)):
        rec = results.get(name)
        if rec and rec.get("ok"):
            r = rec["result"]
            epoch_ms = r["epoch_ms"]
            line = {"metric": metric, "value": epoch_ms, "unit": "ms",
                    "vs_baseline": 1.0, "stage": name,
                    "dtype": r.get("dtype"), "impl": r.get("impl"),
                    # checkpoint-cost columns (sentinel-gated lower-
                    # better, obs/sentinel.py): async save wall +
                    # step-path blocked time of the GCN stage's
                    # checkpoint-v3 rotation
                    "ckpt_save_ms": r.get("ckpt_save_ms"),
                    "ckpt_block_ms": r.get("ckpt_block_ms"),
                    **serve_fields,
                    "stages": stage_summary}
            line.update(_baseline_compare_fields(
                _load_baselines().get(metric), r.get("platform"),
                epoch_ms))
            # regression sentinel: the live value vs the checked-in
            # round history, recorded INTO this round's BENCH artifact
            # so the trajectory carries its own verdicts
            line["sentinel"] = _sentinel_verdict(
                epoch_ms, dtype=r.get("dtype"),
                compile_s=r.get("compile_s"), stage=name)
            print(json.dumps(line))
            return 0
    # no GCN stage completed — promote the freshest in-round on-chip
    # record rather than handing the driver a fifth null (the value is
    # real and attributable; "provenance" says it is not from this
    # invocation).  --cpu runs keep the null path: their failures are
    # local bugs, not tunnel weather.
    errs = {n: results[n].get("error") for n in results
            if not results[n].get("ok")}
    # promotion is strictly a tunnel-weather path: only when a GCN
    # stage was WANTED and attempted/skipped-but-failed.  A micro-only
    # or probe-only run never borrows an old headline number.
    gcn_failed = any(n in errs for n in ("small", "full"))
    if not args.cpu and gcn_failed:
        promo = _promote_stage_record(args, stage_summary, errs)
        if promo is not None:
            promo["sentinel"] = _sentinel_verdict(
                promo["value"], dtype=promo.get("dtype"),
                stage=promo.get("stage"))
            print(json.dumps(promo))
            return 0
    print(json.dumps({"metric": METRIC_FULL, "value": None, "unit": "ms",
                      "vs_baseline": None, "stage": None,
                      "stages": stage_summary, "error": errs}))
    return 1


def main():
    ap = build_parser()
    args = ap.parse_args()
    if args.child:
        run_child(args)
        return
    argv = []
    passthrough = {"--nodes", "--edges", "--layers", "--epochs",
                   "--chunk", "--impl", "--dtype"}
    it = iter(sys.argv[1:])
    for a in it:
        if a.split("=")[0] in passthrough:
            argv.append(a)
            if "=" not in a:
                argv.append(next(it))
        elif a == "--cpu":
            argv.append(a)
    sys.exit(parent(args, argv))


if __name__ == "__main__":
    main()
