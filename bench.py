#!/usr/bin/env python
"""Headline benchmark: full-graph GCN epoch time at Reddit scale.

Protocol (BASELINE.md): the reference repo publishes no numbers, so the
recorded baseline is the reference's canonical workload shape — the
2-layer 602-256-41 GCN on Reddit (232,965 nodes, ~114.8M edges with self
edges, ``example_run.sh:1`` / ``test.sh:8``) — run full-graph,
full-batch with dropout 0.5, Adam, masked softmax-CE, exactly like
``gnn.cc:99-111``'s epoch loop.  When real Reddit data is not available,
a deterministic synthetic graph with matched V/E/degree skew is used;
epoch time is independent of edge identity.

Prints ONE JSON line on stdout:
  {"metric": ..., "value": ..., "unit": "ms", "vs_baseline": ...}

vs_baseline: ratio of the recorded baseline epoch time for this metric
(benchmarks/measured_baselines.json — a real prior measurement on this
hardware, recorded with provenance) to this run's; >1.0 is faster.  If
no baseline has been recorded yet, vs_baseline is 1.0 and the line
carries "baseline": "unrecorded".

Robustness (the TPU is reached through a single-claim tunnel that can be
busy or transiently unavailable): the default entry point is a PARENT
process that runs the real benchmark in a child subprocess under a hard
timeout with bounded retries + backoff, and emits a parseable failure
JSON line instead of a traceback if every attempt fails.  The child is
terminated with SIGTERM, never SIGKILL — hard-killing a claim holder can
wedge the tunnel relay for subsequent processes.
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np


REDDIT_NODES = 232_965
REDDIT_EDGES = 114_848_857  # 114,615,892 + 232,965 self edges

METRIC = "full_graph_gcn_reddit_scale_epoch_time"

_BASELINES_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "benchmarks", "measured_baselines.json")


def build_parser():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=REDDIT_NODES)
    ap.add_argument("--edges", type=int, default=REDDIT_EDGES)
    ap.add_argument("--layers", type=str, default="602-256-41")
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--chunk", type=int, default=512)
    # ell is the production default for big graphs (CLI default too,
    # roc_tpu/train/cli.py); 'blocked' would time a serial-scan path
    # the real training runs never use
    ap.add_argument("--impl", type=str, default="ell")
    ap.add_argument("--dtype", type=str, default="float32")
    ap.add_argument("--small", action="store_true",
                    help="tiny smoke-test scale (CI / CPU)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (skip the TPU claim)")
    ap.add_argument("--child", action="store_true",
                    help="run the benchmark body in this process "
                         "(internal; the default parent mode wraps it "
                         "in timeout+retry)")
    ap.add_argument("--timeout", type=float, default=1500.0,
                    help="per-attempt wall-clock limit (s)")
    ap.add_argument("--retries", type=int, default=2,
                    help="extra attempts after the first failure")
    ap.add_argument("--backoff", type=float, default=60.0,
                    help="initial delay between attempts (s), doubled "
                         "each retry")
    return ap


def _read_baseline():
    """Recorded prior measurement for this metric, or None."""
    try:
        with open(_BASELINES_PATH) as f:
            entry = json.load(f).get(METRIC)
        return float(entry["epoch_ms"]), entry
    except (OSError, KeyError, TypeError, ValueError):
        return None, None


def failure_json(error: str, attempts: int) -> str:
    return json.dumps({
        "metric": METRIC,
        "value": None,
        "unit": "ms",
        "vs_baseline": None,
        "error": error,
        "attempts": attempts,
    })


def parent(args, argv) -> int:
    """Retry/timeout supervisor around the child benchmark process."""
    attempts = args.retries + 1
    delay = args.backoff
    err = "unknown"
    for n in range(attempts):
        print(f"# attempt {n + 1}/{attempts} (timeout {args.timeout:.0f}s)",
              file=sys.stderr)
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child"] + argv,
            stdout=subprocess.PIPE, stderr=sys.stderr, text=True)
        try:
            out, _ = proc.communicate(timeout=args.timeout)
            if proc.returncode == 0:
                for line in reversed(out.splitlines()):
                    line = line.strip()
                    if line.startswith("{"):
                        print(line)
                        return 0
                err = "child exited 0 without a JSON line"
            else:
                err = f"child exited rc={proc.returncode}"
        except subprocess.TimeoutExpired:
            # SIGTERM only: SIGKILL on a TPU-claim holder can wedge the
            # tunnel relay for every subsequent process
            proc.terminate()
            try:
                proc.communicate(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.communicate()
            err = f"timeout after {args.timeout:.0f}s"
        print(f"# attempt {n + 1} failed: {err}", file=sys.stderr)
        if n < attempts - 1:
            print(f"# backing off {delay:.0f}s", file=sys.stderr)
            time.sleep(delay)
            delay *= 2
    print(failure_json(err, attempts))
    return 1


def child(args) -> None:
    if args.small:
        args.nodes, args.edges = 2048, 32768

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from roc_tpu.core.graph import Dataset, random_csr
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer

    layers = [int(x) for x in args.layers.split("-")]
    t0 = time.time()
    dev = jax.devices()[0]
    print(f"# device: {dev.platform} {dev.device_kind} "
          f"(claim {time.time() - t0:.1f}s)", file=sys.stderr)

    t0 = time.time()
    graph = random_csr(args.nodes, args.edges, seed=0)
    rng = np.random.RandomState(1)
    feats = rng.rand(args.nodes, layers[0]).astype(np.float32)
    labels = rng.randint(0, layers[-1], size=args.nodes).astype(np.int32)
    # Reddit-like split: 66% train / 10% val / 24% test
    mask = rng.choice([1, 2, 3], size=args.nodes,
                      p=[0.66, 0.10, 0.24]).astype(np.int32)
    ds = Dataset(graph=graph, features=feats, labels=labels, mask=mask,
                 num_classes=layers[-1], name="reddit-synth")
    print(f"# data gen: {time.time()-t0:.1f}s V={args.nodes} "
          f"E={graph.num_edges}", file=sys.stderr)

    dtype = jnp.float32 if args.dtype == "float32" else jnp.bfloat16
    model = build_gcn(layers, dropout_rate=0.5)
    # eval_every larger than any epoch count: timed epochs are pure
    # train steps, matching the reference's epoch cost (inference runs
    # only every 5th epoch there, gnn.cc:107-110, and is excluded here)
    cfg = TrainConfig(learning_rate=0.01, weight_decay=1e-4,
                      decay_rate=0.97, decay_steps=100,
                      aggr_impl=args.impl, chunk=args.chunk,
                      dtype=dtype, verbose=False, eval_every=1 << 30,
                      symmetric=True)
    t0 = time.time()
    trainer = Trainer(model, ds, cfg)
    trainer.epoch = 1  # skip the epoch-0 eval trigger
    # warmup: compile + 2 steps
    trainer.train(epochs=2)
    trainer.sync()
    print(f"# compile+warmup: {time.time()-t0:.1f}s", file=sys.stderr)

    times = []
    for _ in range(args.epochs):
        t0 = time.time()
        trainer.train(epochs=1)
        trainer.sync()
        times.append((time.time() - t0) * 1000.0)
    epoch_ms = float(np.median(times))
    print(f"# epoch times (ms): {[round(t, 1) for t in times]}",
          file=sys.stderr)
    m = trainer.evaluate()
    print(f"# final train_acc={m['train_acc']:.3f} "
          f"test_acc={m['test_acc']:.3f}", file=sys.stderr)

    baseline_ms, entry = _read_baseline()
    result = {
        "metric": METRIC,
        "value": round(epoch_ms, 2),
        "unit": "ms",
        "vs_baseline": (round(baseline_ms / epoch_ms, 3)
                        if baseline_ms else 1.0),
    }
    if baseline_ms is None:
        result["baseline"] = "unrecorded"
    else:
        result["baseline_ms"] = baseline_ms
        result["baseline_recorded"] = entry.get("recorded", "?")
    print(json.dumps(result))


def main():
    ap = build_parser()
    args = ap.parse_args()
    if args.child:
        child(args)
        return
    argv = [a for a in sys.argv[1:] if a != "--child"]
    sys.exit(parent(args, argv))


if __name__ == "__main__":
    main()
