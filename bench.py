#!/usr/bin/env python
"""Headline benchmark: full-graph GCN epoch time at Reddit scale.

Protocol (BASELINE.md): the reference repo publishes no numbers, so the
recorded baseline is the reference's canonical workload shape — the
2-layer 602-256-41 GCN on Reddit (232,965 nodes, ~114.6M edges with self
edges, ``example_run.sh:1`` / ``test.sh:8``) — run full-graph,
full-batch with dropout 0.5, Adam, masked softmax-CE, exactly like
``gnn.cc:99-111``'s epoch loop.  Since real Reddit data is not available
in this sandbox, a deterministic synthetic graph with matched V/E/degree
skew is used; epoch time is independent of edge identity.

Prints ONE JSON line on stdout:
  {"metric": ..., "value": ..., "unit": "ms", "vs_baseline": ...}

vs_baseline: ratio of the round-1 recorded epoch time (BASELINE_EPOCH_MS,
our own first measurement on a v5e chip — see BASELINE.md) to this run's
epoch time; >1.0 means faster than the recorded baseline.
"""

import argparse
import json
import sys
import time

import numpy as np


# Round-1 recorded epoch time on one TPU v5e chip (ms).  Updated whenever
# the protocol or hardware changes; see BASELINE.md.
BASELINE_EPOCH_MS = 1600.0

REDDIT_NODES = 232_965
REDDIT_EDGES = 114_848_857  # 114,615,892 + 232,965 self edges


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=REDDIT_NODES)
    ap.add_argument("--edges", type=int, default=REDDIT_EDGES)
    ap.add_argument("--layers", type=str, default="602-256-41")
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--chunk", type=int, default=512)
    ap.add_argument("--impl", type=str, default="blocked")
    ap.add_argument("--dtype", type=str, default="float32")
    ap.add_argument("--small", action="store_true",
                    help="tiny smoke-test scale (CI / CPU)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (skip the TPU claim)")
    args = ap.parse_args()

    if args.small:
        args.nodes, args.edges = 2048, 32768

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from roc_tpu.core.graph import random_csr
    from roc_tpu.core.partition import padded_edge_list
    from roc_tpu.models.builder import GraphContext
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.core.graph import Dataset, MASK_TRAIN
    from roc_tpu.train.trainer import TrainConfig, Trainer

    layers = [int(x) for x in args.layers.split("-")]
    dev = jax.devices()[0]
    print(f"# device: {dev.platform} {dev.device_kind}", file=sys.stderr)

    t0 = time.time()
    graph = random_csr(args.nodes, args.edges, seed=0)
    rng = np.random.RandomState(1)
    feats = rng.rand(args.nodes, layers[0]).astype(np.float32)
    labels = rng.randint(0, layers[-1], size=args.nodes).astype(np.int32)
    # Reddit-like split: 66% train / 10% val / 24% test
    mask = rng.choice([1, 2, 3], size=args.nodes,
                      p=[0.66, 0.10, 0.24]).astype(np.int32)
    ds = Dataset(graph=graph, features=feats, labels=labels, mask=mask,
                 num_classes=layers[-1], name="reddit-synth")
    print(f"# data gen: {time.time()-t0:.1f}s V={args.nodes} "
          f"E={graph.num_edges}", file=sys.stderr)

    dtype = jnp.float32 if args.dtype == "float32" else jnp.bfloat16
    model = build_gcn(layers, dropout_rate=0.5)
    # eval_every larger than any epoch count: timed epochs are pure
    # train steps, matching the reference's epoch cost (inference runs
    # only every 5th epoch there, gnn.cc:107-110, and is excluded here)
    cfg = TrainConfig(learning_rate=0.01, weight_decay=1e-4,
                      decay_rate=0.97, decay_steps=100,
                      aggr_impl=args.impl, chunk=args.chunk,
                      dtype=dtype, verbose=False, eval_every=1 << 30,
                      symmetric=True)
    t0 = time.time()
    trainer = Trainer(model, ds, cfg)
    trainer.epoch = 1  # skip the epoch-0 eval trigger
    # warmup: compile + 1 step
    trainer.train(epochs=1)
    jax.block_until_ready(trainer.params)
    print(f"# compile+warmup: {time.time()-t0:.1f}s", file=sys.stderr)

    times = []
    for _ in range(args.epochs):
        t0 = time.time()
        trainer.train(epochs=1)
        jax.block_until_ready(trainer.params)
        times.append((time.time() - t0) * 1000.0)
    epoch_ms = float(np.median(times))
    print(f"# epoch times (ms): {[round(t,1) for t in times]}",
          file=sys.stderr)
    m = trainer.evaluate()
    print(f"# final train_acc={m['train_acc']:.3f} "
          f"test_acc={m['test_acc']:.3f}", file=sys.stderr)

    print(json.dumps({
        "metric": "full_graph_gcn_reddit_scale_epoch_time",
        "value": round(epoch_ms, 2),
        "unit": "ms",
        "vs_baseline": round(BASELINE_EPOCH_MS / epoch_ms, 3),
    }))


if __name__ == "__main__":
    main()
