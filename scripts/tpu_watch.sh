#!/bin/sh
# Background TPU-availability watcher: retry the backend claim with
# backoff, logging the first success.  Used during development to grab
# the (single, tunneled, sometimes-busy) chip as soon as it frees up.
LOG=${1:-/tmp/tpu_watch.log}
: > "$LOG"
n=0
while true; do
  n=$((n + 1))
  echo "[$(date +%H:%M:%S)] attempt $n" >> "$LOG"
  # -k: a probe stuck in the claim wait often ignores SIGTERM — without
  # the kill escalation the watch itself hangs on attempt 1 forever.
  # (KILLing a claim WAITER is safe; the holder-wedge caveat in
  # bench.py applies to processes that already won the claim.)
  if timeout -k 30 180 python - >> "$LOG" 2>&1 <<'EOF'
import jax
ds = jax.devices()
print("CLAIMED:", [(d.platform, d.device_kind) for d in ds])
EOF
  then
    echo "[$(date +%H:%M:%S)] TPU AVAILABLE" >> "$LOG"
    exit 0
  fi
  sleep 60
done
