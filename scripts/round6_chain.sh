#!/bin/sh
# Round-6 measurement queue — the fused-normalization race (ISSUE 1:
# table-baked D^-1/2 scales + fused epilogue).  Run whole or per-step
# on a live chip; each step records its own artifacts
# (benchmarks/*.jsonl / measured_baselines.json).  The acceptance
# claim is >= 1.15x on at least one impl x substrate for the
# aggregation path (chain-X vs fused-X below), or the checked-in
# numbers as a written-up negative result.
cd "$(dirname "$0")/.."
set -x
# 0. compile-wall preflight (ISSUE 7): enumerate the exact program
#    space (ratchet-checked against scripts/lint_baseline.json), then
#    pre-warm the persistent compile cache with AOT lower().compile()
#    so every later step starts warm and writes the warm-state
#    artifact bench.py's probe preflight diffs against — a probe
#    refuses to burn chip deadline on a config whose program set grew
#    since this warm state (dated programspace event, not a blank
#    timeout).
#    exit codes ENFORCED (the rest of the chain records per-step
#    artifacts and may continue past a failed step; the gate must
#    not): a grown program set or a failed/unpersisted prewarm means
#    every later step pays cold first-compiles on the chip
python -m roc_tpu.analysis --json \
  --select compile-explosion,cache-key-drift \
  > benchmarks/programspace_report.json || exit 1
#    concurrency/signal-safety audit (roc-lint level six, jax-free):
#    a runtime whose dispatcher can deadlock or whose stats race must
#    not burn chip deadline; the report doubles as the thread-model
#    artifact (`python -m roc_tpu.report --concurrency <file>`)
python -m roc_tpu.analysis --json --select concurrency \
  > benchmarks/concurrency_report.json || exit 1
#    sharding & replication audit (roc-lint level seven): the
#    replication ledger vs the ratcheted replication_budget plus the
#    (parts, model) mesh-portability worklist — the 2-D-mesh
#    tripwire runs BEFORE chip time, and the artifact renders via
#    `python -m roc_tpu.report --sharding benchmarks/sharding_report.json`
python -m roc_tpu.analysis --json --select sharding \
  > benchmarks/sharding_report.json || exit 1
#    protocol audit & bounded model check (roc-lint level eight,
#    jax-free): the extracted wire vocabulary of the router<->replica
#    channels vs the declared spec tables, plus exhaustive bounded
#    exploration of the router-lifecycle / ckpt-commit / table-swap
#    state machines under crash-at-any-step schedules — a protocol
#    drift or invariant violation must not reach the serve drill or
#    chip stages; the artifact renders via
#    `python -m roc_tpu.report --protocol benchmarks/protocol_report.json`
python -m roc_tpu.analysis --json --select protocol \
  > benchmarks/protocol_report.json || exit 1
#    --jobs stays 1 on the chip host: libtpu owns the accelerator
#    exclusively, so parallel prewarm children would fail backend
#    init (sequential children each claim and release it)
python -m roc_tpu.prewarm --config all || exit 1
#    perf-regression sentinel over the recorded BENCH trajectory
#    (roc_tpu/obs/sentinel.py): refuse to burn chip deadline when the
#    newest recorded round already regressed step/compile time beyond
#    noise — the r01-r05 pattern a human had to notice is a gate now.
#    The live run's own verdict is recorded by bench.py into this
#    round's headline line ("sentinel" field).
python -m roc_tpu.sentinel --json || exit 1
# 0b. serve smoke (ISSUE 11 + 13): export a predictor artifact,
#     cold-load it warm-start (zero new compiles — the artifact's
#     programs were AOT-persisted by the export), drive a 100-query
#     load gen on CPU, and run the kill-a-replica router drill
#     (--drill: 2 replicas, replica 1 SIGKILLed mid-load — zero
#     lost/wrong answers or the chain stops).  Gate ENFORCED: a
#     serving tier that cannot export/load/answer/fail-over on CPU
#     must not reach the chip stages (bench.py's serve stage runs the
#     same harness there).
python benchmarks/micro_serve.py --cpu --queries 100 --drill \
  --out benchmarks/micro_serve_cpu.json > /dev/null || exit 1
#     SLO smoke (ISSUE 17): same export → cold-load path, but with
#     the declared availability/latency objectives armed — 100
#     queries of quiet load-gen must leave Router.health() green
#     (availability 1.0, no burn-rate alert firing).  Gate ENFORCED:
#     an SLO engine that false-alarms on quiet traffic would page on
#     every chip round, and one that cannot go green cannot certify
#     the serve stage's headline numbers.
python benchmarks/micro_serve.py --slo-smoke --cpu \
  --queries 100 --nodes 2000 > /dev/null || exit 1
#     quantized-serving smoke (PR 19): export the precomputed backend
#     at int8 — the measured drift gate must pass (argmax agreement +
#     relative max |Δlogit| vs the fp32 reference; export REFUSES
#     past threshold) — then cold-load the artifact and drive a
#     100-query load gen whose served answers must match the gated
#     values bit-exactly.  Gate ENFORCED: a quantization that drifts,
#     or a cold load that serves different values than were gated,
#     must not reach the chip stages.
python benchmarks/micro_serve.py --quant-smoke --cpu \
  --queries 100 --nodes 2000 > /dev/null || exit 1
#     sharded-serving smoke (PR 20): export --shards 2, cold-load one
#     slice (program keys must match the export-time shard warm set —
#     zero new compiles), then serve a 100-query load gen through a
#     2-replica sharded Router under a per-replica byte cap BELOW the
#     full table, batches forced across the shard boundary.  Gate
#     ENFORCED: answers must be bit-exact via the cross-shard gather
#     leg at availability 1.0 — a fleet that cannot gather across its
#     own shards must not reach the chip stages.
python benchmarks/micro_serve.py --shard-smoke --cpu \
  --queries 100 --nodes 2000 > /dev/null || exit 1
# 1. staged headline refresh (regression guard before the new rows;
#    now includes the serve stage — serve_p50_ms/p99/qps land in the
#    headline line and the sentinel trajectory)
python bench.py
# 2. fused vs chain micro race, UNIFORM substrate, Reddit V/E
python benchmarks/micro_agg.py --dtype mixed \
  --impls chain-ell,fused-ell,chain-sectioned,fused-sectioned \
  --iters 10
# 3. fused vs chain micro race, COMMUNITY substrate (the VERDICT
#    weakness-2 co-track: the headline substrate must include
#    community structure), incl. the bdense tile-scale fold
python benchmarks/micro_agg.py --graph planted:16384 --reorder lpa \
  --dtype mixed \
  --impls chain-sectioned,fused-sectioned,chain-bdense:32:16,fused-bdense:32:16 \
  --a-budget $((6<<30)) --iters 10
# 4. hand-written kernel trio (pre-scale kernel -> ELL DMA kernel ->
#    fused scale+relu epilogue) vs its unfused form — the
#    configuration where the Pallas path races with fusion on its side
python benchmarks/micro_agg.py --dtype float32 \
  --impls chain-pallas,fused-pallas,chain-ell,fused-ell --iters 10
# 5. epoch-level fused race on BOTH substrates (full GCN training
#    epochs; the micro win must transfer end-to-end)
python benchmarks/epoch_community.py --graph random --reorder none \
  --impls sectioned,sectioned+fuse,ell,ell+fuse
python benchmarks/epoch_community.py --min-fill 32 --a-budget $((6<<30)) \
  --bdense-group 16 --impls bdense,bdense+fuse,sectioned,sectioned+fuse
# 6. partitioning race (ISSUE 5): greedy edge sweep vs cost-balanced
#    minimax split on the Zipf power-law + community substrates —
#    max-shard padded shapes, straggler step time, and the distributed
#    epoch race when the host has >= 8 chips.  Acceptance: the cost
#    split reduces modeled max-shard cost AND measured max-shard step
#    time vs greedy (CPU rehearsal: benchmarks/micro_partition_cpu.json)
python benchmarks/micro_partition.py \
  --out benchmarks/micro_partition_chip.json
