#!/bin/sh
# Run-script analog of the reference's test.sh (test.sh:8): positional
# hyperparameters -> the training CLI on a dataset directory.
#   usage: sh scripts/test.sh <lr> <wd> <decay-rate> <dropout> <layers> <epochs> [extra args...]
# The reference's Legion resource flags (-ll:gpu/-ll:cpu/-ll:fsize/
# -ll:zsize) have no TPU analog: XLA owns HBM, --parts picks the mesh.
set -e
LR=$1; WD=$2; DR=$3; DROP=$4; LAYERS=$5; EPOCHS=$6
shift 6 || true
# concurrency/signal-safety preflight (roc-lint level six): pure-AST
# and jax-free, so it fails in milliseconds on a lock-order cycle, a
# predicate-less Condition.wait, or an unsafe signal handler before
# the (slower) trace stage below even starts; the --json report
# carries the discovered thread/lock/handler surface for
# `python -m roc_tpu.report --concurrency <file>`
CONC_REPORT="${TMPDIR:-/tmp}/roc_concurrency_report.json"
python -m roc_tpu.analysis --select concurrency --json \
    > "$CONC_REPORT" || { cat "$CONC_REPORT"; exit 1; }
# sharding & replication preflight (roc-lint level seven): walk both
# trainers' candidate jaxprs on the CPU rig (no compiles) and hold
# the replication ledger against the ratcheted replication_budget —
# a PR that adds a replicated buffer, voids a donation under
# sharding, or re-gathers a constrained tensor to full width fails
# HERE, before chip time; the --json report carries the ledger +
# mesh-portability worklist for
# `python -m roc_tpu.report --sharding <file>`
SHARD_REPORT="${TMPDIR:-/tmp}/roc_sharding_report.json"
python -m roc_tpu.analysis --select sharding --json \
    > "$SHARD_REPORT" || { cat "$SHARD_REPORT"; exit 1; }
# protocol audit + bounded model check preflight (roc-lint level
# eight): pure-AST wire-vocabulary extraction over the serve/ckpt
# state machines plus an exhaustive bounded BFS over crash/interleave
# schedules of the router lifecycle, the v3 two-phase commit, and the
# versioned-table swap — jax-free, millisecond class; a sent-but-
# unhandled wire kind, a dropped field contract, or an invariant
# violation fails HERE.  The --json report carries the surface for
# `python -m roc_tpu.report --protocol <file>`
PROTO_REPORT="${TMPDIR:-/tmp}/roc_protocol_report.json"
python -m roc_tpu.analysis --select protocol --json \
    > "$PROTO_REPORT" || { cat "$PROTO_REPORT"; exit 1; }
# pre-flight static analysis (roc-lint): regressions against the
# perf invariants fail HERE, before any chip time is spent.  The run
# also prints the program-space compile-budget delta vs
# scripts/lint_baseline.json (shrink-only ratchet, red on a tty when
# it grew) — a PR that adds a compiled-program shape shows it before
# the test tier starts.
python -m roc_tpu.analysis --strict
# perf-regression sentinel preflight: median+MAD gate over the
# checked-in BENCH_*.json trajectory (roc_tpu/obs/sentinel.py) — a
# round that regressed step/compile time beyond noise fails HERE,
# before chip time is spent (set -e makes the nonzero exit fatal)
python -m roc_tpu.sentinel --json
# serving SLO smoke preflight (PR 17): export a predictor artifact,
# cold-load it in subprocess replicas, drive a 100-query load gen
# with the declared availability/latency objectives armed, and
# require Router.health() green — a serving tier whose SLO engine
# reports a breach on quiet CPU traffic must not reach chip time
# (set -e makes the nonzero exit fatal)
python benchmarks/micro_serve.py --slo-smoke --cpu \
    --queries 100 --nodes 2000 > /dev/null
# quantized-serving drift-gate preflight (PR 19): export int8 (the
# measured drift gate must pass — export refuses past threshold),
# cold-load, 100-query load gen, served answers bit-equal to the
# gated values — a drifting quantization must not reach chip time
# (set -e makes the nonzero exit fatal)
python benchmarks/micro_serve.py --quant-smoke --cpu \
    --queries 100 --nodes 2000 > /dev/null
# sharded-serving smoke preflight (PR 20): export --shards 2, cold-
# load one slice (zero new compiles — slice shapes ride the same
# bucket quantization), then a 2-replica sharded Router under a byte
# cap below the full table serves a 100-query load gen whose batches
# straddle the shard boundary, bit-exact via the cross-shard gather
# leg — a fleet that cannot gather across its own shards must not
# reach chip time (set -e makes the nonzero exit fatal)
python benchmarks/micro_serve.py --shard-smoke --cpu \
    --queries 100 --nodes 2000 > /dev/null
exec python -m roc_tpu.train.cli \
    -lr "$LR" -decay "$WD" -decay-rate "$DR" -dropout "$DROP" \
    -layers "$LAYERS" -e "$EPOCHS" -file dataset/reddit-dgl "$@"
