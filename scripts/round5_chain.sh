#!/bin/sh
# Round-5 measurement queue — the on-chip runs staged behind the
# 2026-07-31 relay outage (BASELINE.md "Round-5 additions").  Run
# whole or per-step on a live chip; each step records its own
# artifacts (benchmarks/*.jsonl / measured_baselines.json).
cd "$(dirname "$0")/.."
set -x
# 1. staged headline refresh (promotion material for BENCH)
python bench.py
# 2. grouped + u4 micro race, full Reddit V/E, community substrate
python benchmarks/micro_agg.py --graph planted:16384 --reorder lpa \
  --dtype mixed \
  --impls sectioned,bdense:32,bdense:32:8,bdense:32:16,bdense:32:32 \
  --a-budget $((6<<30)) --iters 10
# 3. products-scale GAT via the dh-chunked flat8 layout
python benchmarks/model_zoo.py --config 7 --dtype mixed --remat --epochs 5
# 4. APPNP / GCNII at arxiv shape
python benchmarks/model_zoo.py --config 8 --dtype mixed --epochs 5
python benchmarks/model_zoo.py --config 9 --dtype mixed --epochs 5
# 5. full-epoch community race (bdense first; sectioned is the known
#    cold-compile risk and runs second)
python benchmarks/epoch_community.py --min-fill 32 --a-budget $((6<<30)) \
  --bdense-group 16 --impls bdense,sectioned
# 6. bdense convergence gate at scale (auto-probe pipeline)
python benchmarks/convergence_scale.py --order label
# 7. widened-GIN re-measure (config-5 boundary; budget the compile)
timeout 5400 python benchmarks/model_zoo.py --config 5 --dtype mixed --epochs 5
