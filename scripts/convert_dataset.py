#!/usr/bin/env python
"""Convert public graph datasets to the reference on-disk layout.

The reference trains from ``<prefix>.add_self_edge.lux`` +
``.feats.csv``/``.feats.bin`` + ``.label`` + ``.mask`` files
(``load_task.cu:25-199``; canonical run ``example_run.sh:1`` uses
``dataset/reddit-dgl``).  This script produces that layout from the
standard public distributions:

  cora / citeseer / pubmed   Planetoid raw files (``ind.<name>.x`` ...
                             ``ind.<name>.test.index``) in --raw-dir —
                             the format shipped by the original GCN
                             release and every Planetoid mirror.
  reddit                     DGL's ``reddit_data.npz`` +
                             ``reddit_graph.npz`` in --raw-dir.
  ogbn-arxiv / ogbn-products OGB (requires the ``ogb`` package, which
                             downloads on first use).
  cora-synth                 No inputs: a deterministic Cora-shaped
                             synthetic citation graph (2708 nodes, 1433
                             sparse features, 7 classes, 140/500/1000
                             Planetoid-style split).  The offline
                             stand-in: it exercises the exact same file
                             path + CLI + convergence gate when the
                             real raw files are unavailable.

All graphs are symmetrized and given self edges (the reference's
``.add_self_edge`` convention, ``gnn.cc:756``).

Example (the BASELINE.md config-1 run):
  python scripts/convert_dataset.py --dataset cora --raw-dir raw/ --out data/cora
  python -m roc_tpu.train.cli -file data/cora -layers 1433-16-7 \
      -lr 0.01 -decay 5e-4 -dropout 0.5 -e 200
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from roc_tpu.core.graph import (  # noqa: E402
    MASK_NONE, MASK_TEST, MASK_TRAIN, MASK_VAL, Dataset, add_self_edges,
    from_edge_list, save_dataset)


# ---------------------------------------------------------------- planetoid

def convert_planetoid(raw_dir: str, name: str) -> Dataset:
    """Parse the Planetoid raw distribution (``ind.<name>.{x,y,tx,ty,
    allx,ally,graph,test.index}``) — pickled scipy matrices + an
    adjacency dict.  Includes the standard citeseer fix (isolated test
    nodes missing from ``test.index`` get zero rows)."""
    import pickle
    import scipy.sparse as sp

    def load(ext):
        path = os.path.join(raw_dir, f"ind.{name}.{ext}")
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"{path} not found — download the Planetoid raw files "
                f"for {name!r} into {raw_dir!r}")
        with open(path, "rb") as f:
            return pickle.load(f, encoding="latin1")

    x, y, tx, ty, allx, ally, graph = (
        load(e) for e in ("x", "y", "tx", "ty", "allx", "ally", "graph"))
    # tx/ty rows follow test.index's PERMUTED order; the reorder swap
    # below moves each row to its node id.  reorder = as-read ids,
    # range = sorted — keep BOTH distinct (overwriting reorder turns
    # the swap into a no-op and scrambles every test node).
    test_reorder = np.loadtxt(
        os.path.join(raw_dir, f"ind.{name}.test.index"), dtype=np.int64)
    test_range = np.sort(test_reorder)

    if name == "citeseer":
        # some test ids are missing (isolated vertices): extend tx/ty
        # onto the full contiguous range, placing real rows at their
        # sorted slots; gap nodes get zero features and NO test mask
        full = np.arange(test_range[0], test_range[-1] + 1)
        tx_ext = sp.lil_matrix((len(full), x.shape[1]))
        tx_ext[test_range - test_range[0]] = tx
        tx = tx_ext
        ty_ext = np.zeros((len(full), y.shape[1]), dtype=ty.dtype)
        ty_ext[test_range - test_range[0]] = ty
        ty = ty_ext

    feats = sp.vstack((allx, tx)).tolil()
    feats[test_reorder] = feats[test_range]
    onehot = np.vstack((ally, ty))
    onehot[test_reorder] = onehot[test_range]
    labels = onehot.argmax(axis=1).astype(np.int32)

    num_nodes = feats.shape[0]
    src = np.fromiter((s for s, nbrs in graph.items() for _ in nbrs),
                      dtype=np.int64)
    dst = np.fromiter((d for _, nbrs in graph.items() for d in nbrs),
                      dtype=np.int64)
    keep = (src < num_nodes) & (dst < num_nodes)
    g = add_self_edges(from_edge_list(src[keep], dst[keep], num_nodes,
                                      symmetrize=True))

    mask = np.full(num_nodes, MASK_NONE, dtype=np.int32)
    mask[:y.shape[0]] = MASK_TRAIN                      # 140 for cora
    # next 500 after train, clipped to the allx region (val never
    # reaches into the test tail)
    mask[y.shape[0]:min(y.shape[0] + 500, ally.shape[0])] = MASK_VAL
    mask[test_reorder] = MASK_TEST  # only REAL test ids (1000 for
    #                                 cora; citeseer gap nodes stay None)
    return Dataset(graph=g,
                   features=np.asarray(feats.todense(), dtype=np.float32),
                   labels=labels, mask=mask,
                   num_classes=int(onehot.shape[1]), name=name)


# ---------------------------------------------------------------- reddit

def convert_dgl_reddit(raw_dir: str) -> Dataset:
    """Parse DGL's Reddit distribution: ``reddit_data.npz`` (feature /
    label / node_types where 1=train, 2=val, 3=test) and
    ``reddit_graph.npz`` (scipy sparse adjacency)."""
    import scipy.sparse as sp
    data_p = os.path.join(raw_dir, "reddit_data.npz")
    graph_p = os.path.join(raw_dir, "reddit_graph.npz")
    for p in (data_p, graph_p):
        if not os.path.exists(p):
            raise FileNotFoundError(
                f"{p} not found — download DGL's Reddit files into "
                f"{raw_dir!r}")
    data = np.load(data_p)
    adj = sp.load_npz(graph_p).tocoo()
    num_nodes = data["feature"].shape[0]
    g = add_self_edges(from_edge_list(
        adj.row.astype(np.int64), adj.col.astype(np.int64), num_nodes,
        symmetrize=True))
    types = data["node_types"]
    mask = np.full(num_nodes, MASK_NONE, dtype=np.int32)
    mask[types == 1] = MASK_TRAIN
    mask[types == 2] = MASK_VAL
    mask[types == 3] = MASK_TEST
    labels = data["label"].astype(np.int32)
    return Dataset(graph=g,
                   features=data["feature"].astype(np.float32),
                   labels=labels, mask=mask,
                   num_classes=int(labels.max()) + 1, name="reddit")


# ---------------------------------------------------------------- ogbn

def convert_ogbn(name: str, root: str) -> Dataset:
    """ogbn-arxiv / ogbn-products via the ``ogb`` package (gated: the
    package downloads its own raw data)."""
    try:
        from ogb.nodeproppred import NodePropPredDataset
    except ImportError as e:
        raise SystemExit(
            f"converting {name} needs the 'ogb' package (pip install "
            f"ogb on a connected machine); alternatively convert from "
            f"Planetoid/DGL files or use --dataset cora-synth") from e
    ds = NodePropPredDataset(name=name, root=root)
    split = ds.get_idx_split()
    g0, labels = ds[0]
    num_nodes = int(g0["num_nodes"])
    src, dst = g0["edge_index"][0], g0["edge_index"][1]
    g = add_self_edges(from_edge_list(
        src.astype(np.int64), dst.astype(np.int64), num_nodes,
        symmetrize=True))
    mask = np.full(num_nodes, MASK_NONE, dtype=np.int32)
    mask[split["train"]] = MASK_TRAIN
    mask[split["valid"]] = MASK_VAL
    mask[split["test"]] = MASK_TEST
    labels = labels.reshape(-1).astype(np.int32)
    return Dataset(graph=g, features=g0["node_feat"].astype(np.float32),
                   labels=labels, mask=mask,
                   num_classes=int(labels.max()) + 1, name=name)


# ---------------------------------------------------------------- synthetic

def synthetic_cora(seed: int = 7) -> Dataset:
    """Cora-shaped deterministic citation graph: 2708 nodes, 1433
    binary bag-of-words features, 7 classes, ~5300 undirected citation
    edges (homophilous), Planetoid split (140 train / 500 val / 1000
    test, rest unlabeled).  Labels correlate with both topic-word
    features and neighborhoods, so a 2-layer GCN's semi-supervised
    accuracy is meaningfully above a features-only classifier —
    the same qualitative behavior the real Cora exhibits."""
    V, F, C = 2708, 1433, 7
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, C, size=V).astype(np.int32)
    # citation edges: mostly intra-class (homophily 0.81, the real
    # Cora's measured edge homophily)
    n_edges = 5278
    src = rng.randint(0, V, size=n_edges).astype(np.int64)
    by_class = [np.flatnonzero(labels == c) for c in range(C)]
    same = rng.rand(n_edges) < 0.81
    dst = rng.randint(0, V, size=n_edges).astype(np.int64)
    for c in range(C):
        sel = same & (labels[src] == c)
        dst[sel] = by_class[c][rng.randint(len(by_class[c]),
                                           size=int(sel.sum()))]
    g = add_self_edges(from_edge_list(src, dst, V, symmetrize=True))
    # sparse binary bag-of-words, deliberately weak per-node signal
    # (~4 topic words vs ~22 noise words per doc): a features-only
    # classifier plateaus well below the GCN, so the accuracy gate
    # actually tests aggregation — like the real Cora, where the graph
    # carries ~10 points of test accuracy
    feats = np.zeros((V, F), dtype=np.float32)
    topic_words = rng.randint(0, F, size=(C, 40))
    for v in range(V):
        own = topic_words[labels[v]][rng.rand(40) < 0.10]
        noise = rng.randint(0, F, size=22)
        feats[v, own] = 1.0
        feats[v, noise] = 1.0
    mask = np.full(V, MASK_NONE, dtype=np.int32)
    order = rng.permutation(V)
    mask[order[:140]] = MASK_TRAIN
    mask[order[140:640]] = MASK_VAL
    mask[order[640:1640]] = MASK_TEST
    return Dataset(graph=g, features=feats, labels=labels, mask=mask,
                   num_classes=C, name="cora-synth")


# ---------------------------------------------------------------- karate

# Zachary's karate club (W. W. Zachary, "An Information Flow Model for
# Conflict and Fission in Small Groups", J. Anthropological Research
# 33(4):452-473, 1977): 34 members, 78 friendship edges, and the
# club's REAL post-fission faction split — the smallest real public
# graph dataset, vendored verbatim (public-domain observational data
# shipped by every network-analysis toolkit).  0-indexed; node 0 is
# the instructor ("Mr. Hi"), node 33 the club officer.
_KARATE_EDGES = (
    (0, 1), (0, 2), (0, 3), (0, 4), (0, 5), (0, 6), (0, 7), (0, 8),
    (0, 10), (0, 11), (0, 12), (0, 13), (0, 17), (0, 19), (0, 21),
    (0, 31), (1, 2), (1, 3), (1, 7), (1, 13), (1, 17), (1, 19),
    (1, 21), (1, 30), (2, 3), (2, 7), (2, 8), (2, 9), (2, 13),
    (2, 27), (2, 28), (2, 32), (3, 7), (3, 12), (3, 13), (4, 6),
    (4, 10), (5, 6), (5, 10), (5, 16), (6, 16), (8, 30), (8, 32),
    (8, 33), (9, 33), (13, 33), (14, 32), (14, 33), (15, 32),
    (15, 33), (18, 32), (18, 33), (19, 33), (20, 32), (20, 33),
    (22, 32), (22, 33), (23, 25), (23, 27), (23, 29), (23, 32),
    (23, 33), (24, 25), (24, 27), (24, 31), (25, 31), (26, 29),
    (26, 33), (27, 33), (28, 31), (28, 33), (29, 32), (29, 33),
    (30, 32), (30, 33), (31, 32), (31, 33), (32, 33))

# the documented post-split membership (Zachary's "club" attribute):
# these 17 members joined the officer's club, the rest followed Mr. Hi
_KARATE_OFFICER = frozenset(
    (9, 14, 15, 18, 20, 22, 23, 24, 25, 26, 27, 28, 29, 30, 31, 32, 33))


def karate_club() -> Dataset:
    """The real karate club as a semi-supervised 2-class node
    classification task (the classic GCN demo setup): identity
    features, only the two faction LEADERS labeled for training, a
    2-node val split, the remaining 30 members held out as test —
    predicting the real fission from the topology alone."""
    V = 34
    e = np.asarray(_KARATE_EDGES, dtype=np.int64)
    g = add_self_edges(from_edge_list(e[:, 0], e[:, 1], V,
                                      symmetrize=True))
    labels = np.fromiter((1 if v in _KARATE_OFFICER else 0
                          for v in range(V)), dtype=np.int32, count=V)
    feats = np.eye(V, dtype=np.float32)
    mask = np.full(V, MASK_TEST, dtype=np.int32)
    mask[[0, 33]] = MASK_TRAIN
    mask[[1, 32]] = MASK_VAL
    return Dataset(graph=g, features=feats, labels=labels, mask=mask,
                   num_classes=2, name="karate")


# ---------------------------------------------------------------- main

CONVERTERS = ("cora", "citeseer", "pubmed", "reddit", "ogbn-arxiv",
              "ogbn-products", "cora-synth", "karate")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--dataset", required=True, choices=CONVERTERS)
    ap.add_argument("--raw-dir", default="raw",
                    help="directory with the public raw files")
    ap.add_argument("--out", required=True,
                    help="output prefix (writes <out>.add_self_edge.lux "
                         "etc.)")
    ap.add_argument("--no-csv", action="store_true",
                    help="skip the (large) .feats.csv; .feats.bin is "
                         "always written and preferred by the loader")
    args = ap.parse_args(argv)

    if args.dataset in ("cora", "citeseer", "pubmed"):
        ds = convert_planetoid(args.raw_dir, args.dataset)
    elif args.dataset == "reddit":
        ds = convert_dgl_reddit(args.raw_dir)
    elif args.dataset.startswith("ogbn-"):
        ds = convert_ogbn(args.dataset, args.raw_dir)
    elif args.dataset == "karate":
        ds = karate_club()
    else:
        ds = synthetic_cora()

    os.makedirs(os.path.dirname(os.path.abspath(args.out)), exist_ok=True)
    save_dataset(ds, args.out, csv=not args.no_csv)
    print(f"# wrote {args.out}.add_self_edge.lux  V={ds.graph.num_nodes} "
          f"E={ds.graph.num_edges} in_dim={ds.in_dim} "
          f"classes={ds.num_classes} "
          f"split={int((ds.mask == MASK_TRAIN).sum())}/"
          f"{int((ds.mask == MASK_VAL).sum())}/"
          f"{int((ds.mask == MASK_TEST).sum())}")
    print(f"# train: python -m roc_tpu.train.cli -file {args.out} "
          f"-layers {ds.in_dim}-16-{ds.num_classes} -lr 0.01 "
          f"-decay 5e-4 -dropout 0.5 -e 200")
    return 0


if __name__ == "__main__":
    sys.exit(main())
