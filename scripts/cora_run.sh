#!/bin/sh
# BASELINE.md config 1: 2-layer GCN on Cora through the full file path
# (convert -> .lux/.feats.bin/.label/.mask -> CLI), the analog of the
# reference's example_run.sh convergence check.
#
# With the real Planetoid raw files in raw/ this trains actual Cora
# (literature: ~81% test accuracy):
#   python scripts/convert_dataset.py --dataset cora --raw-dir raw/ --out data/cora
# Without them (offline), the deterministic Cora-shaped synthetic
# stand-in is generated instead; its converged test accuracy is ~93%
# (cleaner label process than real Cora) and the training gate asserts
# >= 85% (tests/test_dataset_convert.py).
set -e
cd "$(dirname "$0")/.."
PREFIX=${1:-data/cora}
[ $# -gt 0 ] && shift
if [ ! -f "$PREFIX.add_self_edge.lux" ]; then
  echo "# $PREFIX not found; generating the synthetic Cora stand-in"
  python scripts/convert_dataset.py --dataset cora-synth \
      --out "$PREFIX" --no-csv
fi
exec python -m roc_tpu.train.cli -file "$PREFIX" -layers 1433-16-7 \
    -lr 0.01 -decay 5e-4 -dropout 0.5 -e 200 --eval-every 50 "$@"
