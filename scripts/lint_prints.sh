#!/bin/sh
# Print ratchet: stdout belongs to the metrics stream.  Thin wrapper
# kept so round-chain scripts and muscle memory don't break — the
# AST heredoc that used to live here migrated verbatim into the
# rule-driven linter (roc_tpu/analysis/ast_lint.py, rule
# 'stdout-print'; see `python -m roc_tpu.analysis --list-rules` for
# the full rule set this is one slice of).
#
# Lints the tree THIS script sits in (the planted-violation test
# copies it into a scratch tree); roc_tpu.analysis itself is imported
# from wherever sys.path finds it, so set PYTHONPATH when the linted
# tree does not contain the analysis package.
set -e
cd "$(dirname "$0")/.."
exec python -m roc_tpu.analysis --root . --select stdout-print
