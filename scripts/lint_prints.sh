#!/bin/sh
# Print ratchet: stdout belongs to the metrics stream.  Fails when a
# bare print() (no file= keyword, i.e. stdout) appears in roc_tpu/
# outside the allowed surfaces:
#   - the event-log console sink (roc_tpu/obs/events.py) — the ONE
#     place diagnostics are rendered (to stderr);
#   - print(format_metrics(...)) — the reference's [INFER] metrics
#     line, the only sanctioned stdout output of a training run;
#   - roc_tpu/report.py — the report CLI, whose stdout IS its product.
# Diagnostics must go through roc_tpu.obs.events.emit (or, for
# pre-bus error paths, print(..., file=sys.stderr)).  AST-based so
# multi-line calls with file=sys.stderr on a later line never
# false-positive.  Wired into the test tier via tests/test_obs.py.
set -e
cd "$(dirname "$0")/.."
exec python - <<'PY'
import ast
import pathlib
import sys

ALLOW_FILES = {"roc_tpu/obs/events.py", "roc_tpu/report.py"}
bad = []
for path in sorted(pathlib.Path("roc_tpu").rglob("*.py")):
    rel = path.as_posix()
    if rel in ALLOW_FILES:
        continue
    tree = ast.parse(path.read_text(), filename=rel)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            continue
        if any(kw.arg == "file" for kw in node.keywords):
            continue  # explicit stream (stderr error paths)
        if (len(node.args) == 1 and isinstance(node.args[0], ast.Call)
                and isinstance(node.args[0].func, ast.Name)
                and node.args[0].func.id == "format_metrics"):
            continue  # the sanctioned [INFER] metrics line
        bad.append(f"{rel}:{node.lineno}")
if bad:
    print("bare print() to stdout in roc_tpu/ — route diagnostics "
          "through roc_tpu.obs.events.emit "
          "(or file=sys.stderr for pre-bus error paths):")
    for b in bad:
        print(f"  {b}")
    sys.exit(1)
print("lint_prints: OK (stdout stays a clean metrics stream)")
PY
