#!/bin/sh
# The reference's known-good Reddit config (example_run.sh:1):
# lr .01, weight-decay 1e-4, lr-decay .97, dropout .5,
# layers 602-256-41, 3000 epochs.
sh "$(dirname "$0")/test.sh" 0.01 0.0001 0.97 0.5 602-256-41 3000 "$@"
