#!/usr/bin/env python
"""Micro-benchmark: 1-D all-parts mesh vs the (parts, model) 2-D mesh.

One wide GCN (F >= 256 — the regime where weight matrices and Adam
moments stop being rounding errors next to the graph blocks) races
every ``candidate_mesh_shapes`` factorization of the SAME device set:

1. **epoch race** — median steady epoch wall ms per shape.  The parts
   axis is the partition count, so each shape retrains with its own
   split; the device set is constant, so the numbers are directly
   comparable.
2. **at-rest HBM race** — measured bytes of params + Adam moments
   resident on device 0 under each shape (the replication the
   auditor's ledger models, read off the live shardings), plus the
   backend's ``memory_stats`` peak when it exposes one (TPU; CPU
   rehearsals report null).

The degenerate all-parts shape (Px1) IS today's 1-D mesh and anchors
the race; ``mesh_epoch_ratio`` = best-2-D / 1-D epoch time (< 1.0
means the model axis pays for itself on this substrate).

Usage: python benchmarks/micro_mesh.py [--cpu] [--out out.json]
The CPU rehearsal artifact lives at benchmarks/micro_mesh_cpu.json
(8 virtual host devices); chip numbers queue through
scripts/round6_chain.sh.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def make_wide_dataset(nodes, degree, dim, classes, seed=0):
    from roc_tpu.core.graph import MASK_NONE, Dataset, random_csr
    g = random_csr(nodes, degree * nodes, seed=seed)
    rng = np.random.RandomState(seed + 1)
    ds = Dataset(graph=g,
                 features=rng.rand(nodes, dim).astype(np.float32),
                 labels=rng.randint(0, classes,
                                    size=nodes).astype(np.int32),
                 mask=np.full(nodes, MASK_NONE, dtype=np.int32),
                 num_classes=classes, name="micro_mesh")
    ds.mask[rng.rand(nodes) < 0.5] = 1
    return ds


def state_bytes_on_device(tr, device) -> int:
    """Measured at-rest bytes of params + Adam moments on ONE device —
    the live counterpart of the auditor's params/opt_state ledger rows
    (model-sharded leaves put only their slice here)."""
    import jax
    total = 0
    for tree in (tr.params, tr.opt_state.m, tr.opt_state.v):
        for leaf in jax.tree_util.tree_leaves(tree):
            for sh in leaf.addressable_shards:
                if sh.device == device:
                    total += int(sh.data.nbytes)
    return total


def mesh_row(ds, parts, model, hidden, epochs, warmup=2):
    """Train the wide GCN on one (parts, model) shape: median steady
    epoch ms + the at-rest state bytes race."""
    import jax
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig
    cfg = TrainConfig(verbose=False, symmetric=True, dropout_rate=0.0,
                      eval_every=1 << 30,
                      mesh="auto" if model == 1 else f"{parts}x{model}")
    tr = DistributedTrainer(
        build_gcn([ds.in_dim, hidden, ds.num_classes],
                  dropout_rate=0.0), ds, parts, cfg)
    tr.train(epochs=warmup)   # compile lap + warmup
    tr.sync()
    times = []
    for _ in range(epochs):
        t0 = time.perf_counter()
        tr.train(epochs=1)
        tr.sync()
        times.append((time.perf_counter() - t0) * 1e3)
    dev = tr.mesh.devices.flat[0]
    stats = getattr(dev, "memory_stats", lambda: None)()
    peak = (stats or {}).get("peak_bytes_in_use")
    return {
        "epoch_ms": round(float(np.median(times)), 2),
        "state_bytes_per_device": state_bytes_on_device(tr, dev),
        "peak_hbm_bytes": int(peak) if peak is not None else None,
        "part_nodes": int(tr.pg.part_nodes),
        "part_edges": int(tr.pg.part_edges),
    }


def mesh_race(ds, num_devices, hidden, epochs):
    """All candidate (parts, model) shapes of ``num_devices`` + the
    1-D-vs-best-2-D summary."""
    from roc_tpu.parallel import candidate_mesh_shapes
    shapes = {}
    for p, m in candidate_mesh_shapes(num_devices):
        shapes[f"{p}x{m}"] = mesh_row(ds, p, m, hidden, epochs)
    one_d = shapes[f"{num_devices}x1"]
    two_d = {k: v for k, v in shapes.items()
             if not k.endswith("x1")}
    best_key = min(two_d, key=lambda k: two_d[k]["epoch_ms"])
    best = two_d[best_key]
    return shapes, {
        "one_d": f"{num_devices}x1",
        "best_2d": best_key,
        "mesh_epoch_ratio": round(
            best["epoch_ms"] / max(one_d["epoch_ms"], 1e-9), 4),
        "state_bytes_ratio": round(
            best["state_bytes_per_device"]
            / max(one_d["state_bytes_per_device"], 1), 4),
        "state_shrunk": bool(best["state_bytes_per_device"]
                             < one_d["state_bytes_per_device"]),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=8192)
    ap.add_argument("--degree", type=int, default=8)
    ap.add_argument("--dim", type=int, default=256,
                    help="input feature width (the wide-model regime)")
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--devices", type=int, default=None,
                    help="race this many devices (default: all)")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax
    dev = jax.devices()[0]
    n = args.devices or len(jax.devices())
    print(f"# device={dev.platform} {dev.device_kind} x{n} "
          f"V={args.nodes} F={args.dim} H={args.hidden}",
          file=sys.stderr)
    ds = make_wide_dataset(args.nodes, args.degree, args.dim,
                           args.classes)
    shapes, win = mesh_race(ds, n, args.hidden, args.epochs)
    for k, row in shapes.items():
        print(f"# {k}: epoch {row['epoch_ms']} ms, state/dev "
              f"{row['state_bytes_per_device']} B", file=sys.stderr)
    result = {"device": f"{dev.platform} {dev.device_kind}",
              "num_devices": n, "config": vars(args),
              "shapes": shapes, "win": win}
    line = json.dumps(result, indent=1)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
