#!/usr/bin/env python
"""Micro-benchmark: neighbor-aggregation implementations at Reddit scale.

The reference's hot loop (``scattergather_kernel.cu:20-76``) is an
O(E * F) irregular CSR sum; this script times our implementations of the
same op on one chip to pick the framework default.

Usage: python benchmarks/micro_agg.py [--nodes N] [--edges E] [--dim F]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def bench(fn, iters=10):
    """Median wall ms.  Synchronizes by fetching a scalar reduction of
    the output — ``block_until_ready`` does not reliably synchronize
    under the axon tunnel platform, so device->host fetch is the only
    trustworthy barrier (its ~constant overhead is reported separately
    by --calibrate)."""
    import jax.numpy as jnp
    out = fn()
    float(jnp.sum(out))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn()
        float(jnp.sum(out))
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=232_965)
    ap.add_argument("--edges", type=int, default=114_848_857)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--dtype", type=str, default="float32")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--impls", type=str,
                    default="ell,pallas,scan:2048,scan:4096,blocked:1024")
    ap.add_argument("--seg-rows", type=int, default=131_072,
                    help="sectioned carry-scan chunk size (sub-rows)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from roc_tpu.core.graph import random_csr
    from roc_tpu.core.partition import padded_edge_list
    from roc_tpu.ops.aggregate import aggregate, aggregate_ell

    V, E, F = args.nodes, args.edges, args.dim
    dev = jax.devices()[0]
    print(f"# device={dev.platform} {dev.device_kind} V={V} E={E} F={F}")
    # fetch-overhead calibration: trivial computation + same sync path
    z = jnp.zeros((1024, F))
    f0 = jax.jit(lambda x: x + 1.0)
    print(f"# sync overhead ~{bench(lambda: f0(z), args.iters):.1f} ms "
          f"(subtract from rows below)")
    g = random_csr(V, E, seed=0)
    dtype = getattr(jnp, args.dtype)
    feats_np = np.random.RandomState(0).rand(V + 1, F).astype(np.float32)
    feats_np[-1] = 0
    feats = jnp.asarray(feats_np, dtype=dtype)
    gb = E * F * feats.dtype.itemsize / 1e9

    ell_cache = {}

    def get_ell():
        if "t" not in ell_cache:
            from roc_tpu.core.ell import ell_from_graph
            t0 = time.time()
            ell = ell_from_graph(g.row_ptr, g.col_idx, V)
            ell_cache["prep"] = time.time() - t0
            ell_cache["t"] = (
                tuple(jnp.asarray(i[0]) for i in ell.idx),
                jnp.asarray(ell.row_pos[0]))
        return ell_cache["t"], ell_cache["prep"]

    for spec in args.impls.split(","):
        if ":" in spec:
            impl, chunk = spec.split(":")
            chunk = int(chunk)
        else:
            impl, chunk = spec, 1024
        if impl == "sectioned":
            # sectioned:ROWS overrides the section size (in source
            # rows) — the dtype-aware sweep: bf16 tables are half the
            # bytes, so sections can be 2x the rows for the same VMEM
            # footprint (fewer sections = fewer scatter passes + less
            # sub-row padding)
            from roc_tpu.core.ell import (SECTION_ROWS_DEFAULT,
                                          sectioned_from_graph)
            from roc_tpu.ops.aggregate import aggregate_ell_sect
            sec_rows = chunk if ":" in spec else SECTION_ROWS_DEFAULT
            t0 = time.time()
            sect = sectioned_from_graph(g.row_ptr, g.col_idx, V,
                                        section_rows=sec_rows,
                                        seg_rows=args.seg_rows)
            prep = time.time() - t0
            sidx, sdst, meta = sect.as_jax()
            # tables as ARGUMENTS: closure/default-arg capture embeds
            # them as HLO constants and overflows the remote-compile
            # request past ~100 MB of tables
            f = jax.jit(lambda x, i, d:
                        aggregate_ell_sect(x, i, d, meta, V))
            ms = bench(lambda: f(feats, sidx, sdst), args.iters)
            print(f"{spec:16s} {ms:9.2f} ms   {gb/ms*1e3:7.1f} GB/s "
                  f"(prep {prep:.1f}s)")
            continue
        if impl == "ell":
            (idx, pos), prep = get_ell()
            f = jax.jit(lambda x, i, p: aggregate_ell(x, i, p, V))
            ms = bench(lambda: f(feats, idx, pos), args.iters)
            print(f"{spec:16s} {ms:9.2f} ms   {gb/ms*1e3:7.1f} GB/s "
                  f"(prep {prep:.1f}s)")
            continue
        if impl == "pallas":
            # the one-launch DMA kernel (kernels/ell_spmm.py), compiled
            # (not interpret) — the head-to-head VERDICT round 1 asked
            # for: same ELL tables as the XLA 'ell' row above
            from roc_tpu.kernels.ell_spmm import ell_aggregate_pallas
            (idx, pos), prep = get_ell()
            f = jax.jit(lambda x, i, p:
                        ell_aggregate_pallas(x, i, p, V))
            try:
                ms = bench(lambda: f(feats, idx, pos), args.iters)
                print(f"{spec:16s} {ms:9.2f} ms   {gb/ms*1e3:7.1f} GB/s "
                      f"(prep {prep:.1f}s)")
            except Exception as e:  # noqa: BLE001 - report and continue
                print(f"{spec:16s} FAILED: {type(e).__name__}: {e}")
            continue
        src, dst = padded_edge_list(g, multiple=chunk)
        srcj, dstj = jnp.asarray(src), jnp.asarray(dst)
        f = jax.jit(lambda x, s, d, i=impl, c=chunk:
                    aggregate(x, s, d, V, impl=i, chunk=c))
        try:
            ms = bench(lambda: f(feats, srcj, dstj), args.iters)
            print(f"{spec:16s} {ms:9.2f} ms   {gb/ms*1e3:7.1f} GB/s")
        except Exception as e:  # noqa: BLE001 - report and continue
            print(f"{spec:16s} FAILED: {type(e).__name__}: {e}")


if __name__ == "__main__":
    main()
