#!/usr/bin/env python
"""Micro-benchmark: neighbor-aggregation implementations at Reddit scale.

The reference's hot loop (``scattergather_kernel.cu:20-76``) is an
O(E * F) irregular CSR sum; this script times our implementations of the
same op on one chip to pick the framework default.

Usage: python benchmarks/micro_agg.py [--nodes N] [--edges E] [--dim F]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def bench(fn, iters=10):
    """Median wall ms.  Synchronizes by fetching a scalar reduction of
    the output — ``block_until_ready`` does not reliably synchronize
    under the axon tunnel platform, so device->host fetch is the only
    trustworthy barrier (its ~constant overhead is reported separately
    by --calibrate)."""
    import jax.numpy as jnp
    out = fn()
    float(jnp.sum(out))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn()
        float(jnp.sum(out))
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=232_965)
    ap.add_argument("--edges", type=int, default=114_848_857)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--dtype", type=str, default="float32")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--impls", type=str,
                    default="ell,pallas,scan:2048,scan:4096,blocked:1024")
    ap.add_argument("--seg-rows", type=int, default=131_072,
                    help="sectioned carry-scan chunk size (sub-rows)")
    from _substrates import GRAPH_SPEC_HELP
    ap.add_argument("--graph", type=str, default="random",
                    help=GRAPH_SPEC_HELP)
    ap.add_argument("--reorder", type=str, default="none",
                    help="none | bfs | lpa — relabel vertices before "
                         "table build (core/reorder.py)")
    ap.add_argument("--a-budget", type=int, default=2 << 30,
                    help="bdense uint8 A-table byte cap (densest "
                         "blocks kept; 0 = uncapped).  The 2 GiB "
                         "default binds at Reddit scale: 6 GiB + "
                         "bdense:32 lifts dense_frac 0.52 -> 0.81")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (the env var alone is "
                         "overridden by the axon sitecustomize)")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from _substrates import graph_from_spec, reorder_graph
    from roc_tpu.core.partition import padded_edge_list
    from roc_tpu.ops.aggregate import aggregate, aggregate_ell

    V, E, F = args.nodes, args.edges, args.dim
    dev = jax.devices()[0]
    print(f"# device={dev.platform} {dev.device_kind} V={V} E={E} F={F}")
    # fetch-overhead calibration: trivial computation + same sync path
    z = jnp.zeros((1024, F))
    f0 = jax.jit(lambda x: x + 1.0)
    print(f"# sync overhead ~{bench(lambda: f0(z), args.iters):.1f} ms "
          f"(subtract from rows below)")
    g = graph_from_spec(args.graph, V, E)
    g, reorder_s = reorder_graph(
        g, args.reorder, cache_key=f"{args.graph}_{V}_{E}")
    if reorder_s:
        print(f"# {args.reorder} reorder: {reorder_s:.1f}s")
    # 'mixed' is the TRAINER's dtype flag (fp32 params + bf16 compute);
    # here the aggregation input itself is what's typed, so map it to
    # bf16 instead of dying after a multi-minute reorder pass
    dtype = jnp.bfloat16 if args.dtype == "mixed" else getattr(jnp, args.dtype)
    feats_np = np.random.RandomState(0).rand(V + 1, F).astype(np.float32)
    feats_np[-1] = 0
    feats = jnp.asarray(feats_np, dtype=dtype)
    gb = E * F * feats.dtype.itemsize / 1e9

    ell_cache = {}

    def get_ell():
        if "t" not in ell_cache:
            from roc_tpu.core.ell import ell_from_graph
            t0 = time.time()
            ell = ell_from_graph(g.row_ptr, g.col_idx, V)
            ell_cache["prep"] = time.time() - t0
            ell_cache["table"] = ell
            ell_cache["t"] = (
                tuple(jnp.asarray(i[0]) for i in ell.idx),
                jnp.asarray(ell.row_pos[0]))
        return ell_cache["t"], ell_cache["prep"]

    # the fused-normalization race (chain-IMPL vs fused-IMPL rows):
    # d = deg^-1/2 over the dst-major CSR, the same vector the GCN
    # sandwich applies on both sides
    from roc_tpu.ops.norm import inv_sqrt_degree_np
    d_np = inv_sqrt_degree_np(np.diff(g.row_ptr))
    d_ext = np.concatenate([d_np, np.zeros(1, np.float32)])
    dj = jnp.asarray(d_np, dtype=dtype)
    dj_ext = jnp.asarray(d_ext, dtype=dtype)
    dj32 = jnp.asarray(d_np)  # fp32, for the pallas epilogue kernel

    for spec in args.impls.split(","):
        parts = spec.split(":")
        impl = parts[0]
        chunk = int(parts[1]) if len(parts) > 1 else 1024
        if impl.startswith(("chain-", "fused-")):
            # the fused-normalization race (ISSUE 1): 'chain-X' runs
            # the UNFUSED GCN sandwich relu(d * agg_X(d * x)) as the
            # model's separate ops would; 'fused-X' runs the same
            # chain with the D^-1/2 scales baked into the tables
            # (ell/sectioned weight tables, bdense in-register tile
            # scales, the hand-written kernel trio for pallas).
            # Specs: {chain,fused}-{ell,sectioned,bdense,pallas};
            # bdense takes :MINFILL[:GROUP] like the plain row.
            mode, base = impl.split("-", 1)
            t0 = time.time()
            try:
                if base in ("ell", "pallas"):
                    (idx, pos), _ = get_ell()
                    if base == "pallas":
                        from roc_tpu.kernels.ell_spmm import \
                            ell_aggregate_pallas
                        from roc_tpu.kernels.graphnorm import (
                            fused_ell_aggregate_pallas,
                            indegree_norm_pallas, scale_act_pallas)
                        degj = jnp.asarray(np.concatenate(
                            [np.diff(g.row_ptr).astype(np.int32),
                             np.zeros(1, np.int32)]))
                        if mode == "fused":
                            def run_fn(x, i, p):
                                xs = indegree_norm_pallas(x, degj)
                                return fused_ell_aggregate_pallas(
                                    xs, i, p, V, dj32, act="relu")
                        else:
                            def run_fn(x, i, p):
                                y = ell_aggregate_pallas(
                                    x * dj_ext[:, None], i, p, V)
                                return jax.nn.relu(y * dj[:, None])
                        f = jax.jit(run_fn)
                        run = lambda: f(feats, idx, pos)
                    elif mode == "fused":
                        from roc_tpu.core.ell import ell_weight_tables
                        tab = ell_cache["table"]
                        w = tuple(jnp.asarray(a[0]) for a in
                                  ell_weight_tables(tab, d_np[None, :],
                                                    d_np))
                        f = jax.jit(lambda x, i, p, ww: jax.nn.relu(
                            aggregate_ell(x, i, p, V, ell_w=ww)))
                        run = lambda: f(feats, idx, pos, w)
                    else:
                        f = jax.jit(lambda x, i, p: jax.nn.relu(
                            aggregate_ell(x * dj_ext[:, None], i, p, V)
                            * dj[:, None]))
                        run = lambda: f(feats, idx, pos)
                elif base == "sectioned":
                    from roc_tpu.core.ell import sectioned_from_graph
                    from roc_tpu.ops.aggregate import aggregate_ell_sect
                    sect = sectioned_from_graph(
                        g.row_ptr, g.col_idx, V, seg_rows=args.seg_rows)
                    sidx, sdst, meta = sect.as_jax()
                    if mode == "fused":
                        w = tuple(jnp.asarray(a) for a in
                                  sect.weight_tables(d_np, d_np))
                        f = jax.jit(lambda x, i, dd, ww: jax.nn.relu(
                            aggregate_ell_sect(x, i, dd, meta, V,
                                               sect_w=ww)))
                        run = lambda: f(feats, sidx, sdst, w)
                    else:
                        f = jax.jit(lambda x, i, dd: jax.nn.relu(
                            aggregate_ell_sect(x * dj_ext[:, None], i,
                                               dd, meta, V)
                            * dj[:, None]))
                        run = lambda: f(feats, sidx, sdst)
                elif base == "bdense":
                    from roc_tpu.core.ell import sectioned_from_graph
                    from roc_tpu.ops.aggregate import aggregate_ell_sect
                    from roc_tpu.ops.blockdense import (
                        aggregate_block_dense, plan_blocks_packed)
                    min_fill = int(parts[1]) if len(parts) > 1 else 64
                    group = int(parts[2]) if len(parts) > 2 else 1
                    plan = plan_blocks_packed(
                        g.row_ptr, g.col_idx, V, min_fill=min_fill,
                        a_budget_bytes=args.a_budget or None,
                        group=group)
                    sect = sectioned_from_graph(plan.res_row_ptr,
                                                plan.res_col, V)
                    sidx, sdst, meta = sect.as_jax()
                    ab, sb, db = (jnp.asarray(plan.a_blocks),
                                  jnp.asarray(plan.src_blk),
                                  jnp.asarray(plan.dst_blk))
                    if mode == "fused":
                        dd_pad = np.zeros(plan.vpad, np.float32)
                        dd_pad[:V] = d_np
                        ddj = jnp.asarray(dd_pad)
                        w = tuple(jnp.asarray(a) for a in
                                  sect.weight_tables(d_np, d_np))

                        def run_fn(x, a, s, d, i, dd, ww):
                            y = aggregate_block_dense(
                                x, a, s, d, V, plan.vpad, group=group,
                                out_dtype=x.dtype, scale_dst=ddj,
                                scale_src=ddj)
                            return jax.nn.relu(
                                y + aggregate_ell_sect(x, i, dd, meta,
                                                       V, sect_w=ww))
                        f = jax.jit(run_fn)
                        run = lambda: f(feats, ab, sb, db, sidx, sdst, w)
                    else:
                        def run_fn(x, a, s, d, i, dd):
                            xs = x * dj_ext[:, None]
                            y = aggregate_block_dense(
                                xs, a, s, d, V, plan.vpad, group=group,
                                out_dtype=x.dtype)
                            y = y + aggregate_ell_sect(xs, i, dd,
                                                       meta, V)
                            return jax.nn.relu(y * dj[:, None])
                        f = jax.jit(run_fn)
                        run = lambda: f(feats, ab, sb, db, sidx, sdst)
                else:
                    print(f"{spec:16s} REJECTED: unknown base impl "
                          f"{base!r} for {mode}- spec")
                    continue
                prep = time.time() - t0
                ms = bench(run, args.iters)
                print(f"{spec:16s} {ms:9.2f} ms   {gb/ms*1e3:7.1f} GB/s "
                      f"(prep {prep:.1f}s)")
            except Exception as e:  # noqa: BLE001 - report and continue
                print(f"{spec:16s} FAILED: {type(e).__name__}: "
                      f"{str(e)[:200]}")
            continue
        if impl == "sectioned":
            # sectioned:ROWS overrides the section size (in source
            # rows) — the dtype-aware sweep: bf16 tables are half the
            # bytes, so sections can be 2x the rows for the same VMEM
            # footprint (fewer sections = fewer scatter passes + less
            # sub-row padding)
            from roc_tpu.core.ell import (SECTION_ROWS_DEFAULT,
                                          sectioned_from_graph)
            from roc_tpu.ops.aggregate import aggregate_ell_sect
            sec_rows = chunk if ":" in spec else SECTION_ROWS_DEFAULT
            t0 = time.time()
            sect = sectioned_from_graph(g.row_ptr, g.col_idx, V,
                                        section_rows=sec_rows,
                                        seg_rows=args.seg_rows)
            prep = time.time() - t0
            sidx, sdst, meta = sect.as_jax()
            # tables as ARGUMENTS: closure/default-arg capture embeds
            # them as HLO constants and overflows the remote-compile
            # request past ~100 MB of tables
            f = jax.jit(lambda x, i, d:
                        aggregate_ell_sect(x, i, d, meta, V))
            ms = bench(lambda: f(feats, sidx, sdst), args.iters)
            print(f"{spec:16s} {ms:9.2f} ms   {gb/ms*1e3:7.1f} GB/s "
                  f"(prep {prep:.1f}s)")
            continue
        if impl in ("sectw", "sectu16", "sectsplit"):
            # sectioned-layout variants (VERDICT r4 gather levers):
            #   sectw:W      sub-row width W instead of 8
            #   sectu16[:W]  uint16 section-local indices (section_rows
            #                65,535 so the dummy id fits), sub-row
            #                width W (default 8)
            #   sectsplit[:W] W independent [N]-index gathers instead
            #                of the [N, W] block gather
            # The :W suffix means sub-row width for ALL three variants
            # (round-4 advisor: sectu16:16 used to silently bench width
            # 8 under a width-16 label).
            from roc_tpu.core.ell import (SECTION_ROWS_DEFAULT,
                                          sectioned_from_graph)
            from roc_tpu.ops.aggregate import (aggregate_ell_sect,
                                               aggregate_ell_sect_split)
            if impl == "sectw" and ":" not in spec:
                # a bare 'sectw' measures the default width-8 config —
                # identical to 'sectioned' — and would land a mislabeled
                # row in the sweep artifact
                print(f"{spec:16s} REJECTED: 'sectw' needs an explicit "
                      f"width — use sectw:W (sectw:8 == default)")
                continue
            sub_w = chunk if ":" in spec else 8
            sec_rows = (65_535 if impl == "sectu16"
                        else SECTION_ROWS_DEFAULT)
            t0 = time.time()
            sect = sectioned_from_graph(g.row_ptr, g.col_idx, V,
                                        section_rows=sec_rows,
                                        seg_rows=args.seg_rows,
                                        sub_w=sub_w)
            if impl == "sectu16":
                sect = sect.with_idx_dtype(np.uint16)
            prep = time.time() - t0
            sidx, sdst, meta = sect.as_jax()
            agg = (aggregate_ell_sect_split if impl == "sectsplit"
                   else aggregate_ell_sect)
            f = jax.jit(lambda x, i, d, a=agg: a(x, i, d, meta, V))
            try:
                ms = bench(lambda: f(feats, sidx, sdst), args.iters)
                print(f"{spec:16s} {ms:9.2f} ms   {gb/ms*1e3:7.1f} GB/s "
                      f"(prep {prep:.1f}s, "
                      f"{sect.padded_edges/1e6:.1f}M slots)")
            except Exception as e:  # noqa: BLE001 - report and continue
                print(f"{spec:16s} FAILED: {type(e).__name__}: {e}")
            continue
        if impl == "bdense":
            # block-dense MXU path: dense [128,128] adjacency tiles as
            # bf16 batched matmuls + the residual through the sectioned
            # gather (VERDICT r4 #1).  bdense:MINFILL sets the dense
            # threshold (edges per block; default 64 ~ the measured
            # row-rate breakeven); bdense:MINFILL:GROUP reduces GROUP
            # dst-sharing blocks per output-tile update
            # (pad_plan_groups — cuts the [128,F] fp32 RMW traffic).
            # Occupancy stats print with the row — they are the
            # claim's evidence either way.
            from roc_tpu.core.ell import sectioned_from_graph
            from roc_tpu.ops.aggregate import aggregate_ell_sect
            from roc_tpu.ops.blockdense import (BLOCK,
                                                aggregate_block_dense,
                                                plan_blocks_packed)
            min_fill = chunk if len(parts) > 1 else 64
            group = int(parts[2]) if len(parts) > 2 else 1
            t0 = time.time()
            plan = plan_blocks_packed(
                g.row_ptr, g.col_idx, V, min_fill=min_fill,
                a_budget_bytes=args.a_budget or None, group=group)
            u4 = plan.a_blocks.shape[-1] == BLOCK // 2
            occ = plan.occupancy()
            res_frac = 1.0 - occ["dense_frac"]
            have_residual = plan.res_col.shape[0] > 0
            if have_residual:
                sect = sectioned_from_graph(plan.res_row_ptr,
                                            plan.res_col, V)
                sidx, sdst, meta = sect.as_jax()
            prep = time.time() - t0
            # tables as ARGUMENTS, never closure captures: captures
            # embed them as HLO constants (slow folding here, HTTP-413
            # remote-compile overflow at scale — same rule as the
            # sectioned branch above)
            ab = jnp.asarray(plan.a_blocks)
            sb = jnp.asarray(plan.src_blk)
            db = jnp.asarray(plan.dst_blk)

            if have_residual:
                def agg_bd(x, a, s, d, i, dd):
                    dense = aggregate_block_dense(x, a, s, d, V,
                                                  plan.vpad,
                                                  group=group)
                    return dense + aggregate_ell_sect(x, i, dd, meta, V)
                f = jax.jit(agg_bd)
                run = lambda: f(feats, ab, sb, db, sidx, sdst)
            else:
                f = jax.jit(lambda x, a, s, d: aggregate_block_dense(
                    x, a, s, d, V, plan.vpad, group=group))
                run = lambda: f(feats, ab, sb, db)
            try:
                ms = bench(run, args.iters)
                gpad = (f", group {group} (+{plan.pad_blocks} pad)"
                        if group > 1 else "")
                gpad += ", A u4" if u4 else ""
                print(f"{spec:16s} {ms:9.2f} ms   {gb/ms*1e3:7.1f} GB/s "
                      f"(prep {prep:.1f}s, {occ['n_blocks']} blocks, "
                      f"fill {occ['mean_fill']}, dense "
                      f"{occ['dense_frac']:.0%}, residual "
                      f"{res_frac:.0%}{gpad})")
            except Exception as e:  # noqa: BLE001 - report and continue
                print(f"{spec:16s} FAILED: {type(e).__name__}: "
                      f"{str(e)[:200]}")
            continue
        if impl == "hub":
            # hub-split: top-K most referenced sources aggregated as a
            # dense [V, K] count-matrix matmul on the MXU; the residual
            # (non-hub) edges through the sectioned gather.  Pays off
            # only on source-skewed graphs (--graph skew / real
            # power-law data); uniform sources put ~K/V of the edge
            # mass on the hubs.
            K = chunk if ":" in spec else 4096
            from roc_tpu.core.ell import sectioned_from_graph
            from roc_tpu.ops.aggregate import aggregate_ell_sect
            t0 = time.time()
            freq = np.bincount(g.col_idx, minlength=V)
            hubs = np.argsort(-freq)[:K].astype(np.int64)
            cover = float(freq[hubs].sum()) / E
            is_hub = np.zeros(V, dtype=bool)
            is_hub[hubs] = True
            hub_rank = np.zeros(V, dtype=np.int64)
            hub_rank[hubs] = np.arange(K)
            deg = np.diff(g.row_ptr)
            dst_all = np.repeat(np.arange(V, dtype=np.int64), deg)
            hub_sel = is_hub[g.col_idx]
            M = np.zeros((V, K), dtype=np.float32)
            np.add.at(M, (dst_all[hub_sel],
                          hub_rank[g.col_idx[hub_sel]]), 1.0)
            rest_col = g.col_idx[~hub_sel]
            rest_dst = dst_all[~hub_sel]
            rest_ptr = np.zeros(V + 1, dtype=np.int64)
            np.cumsum(np.bincount(rest_dst, minlength=V),
                      out=rest_ptr[1:])
            sect = sectioned_from_graph(rest_ptr, rest_col, V,
                                        seg_rows=args.seg_rows)
            prep = time.time() - t0
            sidx, sdst, meta = sect.as_jax()
            Mj = jnp.asarray(M, dtype=feats.dtype)
            hubj = jnp.asarray(hubs)

            def hub_agg(x, Mx, i, d):
                import jax as _jax
                dense = _jax.lax.dot_general(
                    Mx, x[hubj], (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32).astype(x.dtype)
                return dense + aggregate_ell_sect(x, i, d, meta, V)

            f = jax.jit(hub_agg)
            try:
                ms = bench(lambda: f(feats, Mj, sidx, sdst), args.iters)
                print(f"{spec:16s} {ms:9.2f} ms   {gb/ms*1e3:7.1f} GB/s "
                      f"(prep {prep:.1f}s, hub coverage "
                      f"{cover*100:.1f}% of E)")
            except Exception as e:  # noqa: BLE001 - report and continue
                print(f"{spec:16s} FAILED: {type(e).__name__}: {e}")
            continue
        if impl == "ell":
            (idx, pos), prep = get_ell()
            f = jax.jit(lambda x, i, p: aggregate_ell(x, i, p, V))
            ms = bench(lambda: f(feats, idx, pos), args.iters)
            print(f"{spec:16s} {ms:9.2f} ms   {gb/ms*1e3:7.1f} GB/s "
                  f"(prep {prep:.1f}s)")
            continue
        if impl == "pallas":
            # the one-launch DMA kernel (kernels/ell_spmm.py), compiled
            # (not interpret) — the head-to-head VERDICT round 1 asked
            # for: same ELL tables as the XLA 'ell' row above
            from roc_tpu.kernels.ell_spmm import ell_aggregate_pallas
            (idx, pos), prep = get_ell()
            f = jax.jit(lambda x, i, p:
                        ell_aggregate_pallas(x, i, p, V))
            try:
                ms = bench(lambda: f(feats, idx, pos), args.iters)
                print(f"{spec:16s} {ms:9.2f} ms   {gb/ms*1e3:7.1f} GB/s "
                      f"(prep {prep:.1f}s)")
            except Exception as e:  # noqa: BLE001 - report and continue
                print(f"{spec:16s} FAILED: {type(e).__name__}: {e}")
            continue
        src, dst = padded_edge_list(g, multiple=chunk)
        srcj, dstj = jnp.asarray(src), jnp.asarray(dst)
        f = jax.jit(lambda x, s, d, i=impl, c=chunk:
                    aggregate(x, s, d, V, impl=i, chunk=c))
        try:
            ms = bench(lambda: f(feats, srcj, dstj), args.iters)
            print(f"{spec:16s} {ms:9.2f} ms   {gb/ms*1e3:7.1f} GB/s")
        except Exception as e:  # noqa: BLE001 - report and continue
            print(f"{spec:16s} FAILED: {type(e).__name__}: {e}")


if __name__ == "__main__":
    main()
