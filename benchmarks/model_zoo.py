#!/usr/bin/env python
"""One-chip epoch-time measurements for BASELINE.md configs 3-5 shapes.

The bench.py headline covers configs 1-2 (Cora accuracy gate + Reddit
GCN).  This script times the remaining model-family configs on
synthetic graphs with the real datasets' V/E/F shapes (epoch time is
independent of edge identity):

  3  GraphSAGE-mean, ogbn-arxiv shape   (169k nodes, 2.3M directed
     edges -> ~4.6M symmetric+self, 128 feats, 40 classes)
  4  GCN, ogbn-products shape           (2.45M nodes, ~126M
     symmetric+self edges, 100 feats, 47 classes) — the reference
     runs this 4-way; one chip is the per-device slice x4 workload
  5  GIN sum-aggregation + MLP, Amazon-2M shape (same graph family as
     products; 2-layer GIN MLP)

Usage: python benchmarks/model_zoo.py [--config 3|4|5] [--epochs N]
Appends results to benchmarks/model_zoo.jsonl.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

CONFIGS = {
    "3": dict(model="sage", nodes=169_343, edges=4_600_000,
              layers=(128, 256, 40)),
    "4": dict(model="gcn", nodes=2_449_029, edges=126_000_000,
              layers=(100, 256, 47)),
    # 5: GIN's default MLP hidden changed in round 5 (output layer:
    # 47 -> 256; a class-count-wide biasless ReLU bottleneck could die
    # per-class — models/gin.py).  The recorded 6,023 ms mixed record
    # predates the widening and needs a re-measure; the extra
    # [2.45M, 256] activation may also move the fits-in-HBM boundary
    # (the autopilot will say).
    "5": dict(model="gin", nodes=2_449_029, edges=126_000_000,
              layers=(100, 256, 47)),
    # 6: GAT at ogbn-arxiv shape — the attention family (beyond the
    # reference's sum-only aggregation; ops/attention.py).  Attention
    # needs the ELL tables, so impl='auto' resolves through the
    # trainer's resolve_attention_impl override, not the size split.
    "6": dict(model="gat", nodes=169_343, edges=4_600_000,
              layers=(128, 256, 40)),
    # 7: GAT at the products/Amazon-2M shape — the attention capability
    # bound on one chip.  History (v5e, 2026-07-30): the per-width
    # bucket path OOMed its backward residuals (fixed by the scan-body
    # remat in ops/attention.py), then exceeded practical remote
    # compile time (>40 min — one checkpointed scan per width bucket,
    # doubled by autodiff).  The uniform flat8 layout exists for
    # exactly this config (HLO 4849 -> 511 lines, compile_probe.py);
    # with impl left at 'auto' the trainer now routes E=126M attention
    # to 'attn_flat8'.  2026-07-31: the flat8 numerator carry OOMed by
    # 885M at this V/F (fixed by the dh-chunked numerator,
    # resolve_dh_chunk); re-measure pending a tunnel window.
    "7": dict(model="gat", nodes=2_449_029, edges=126_000_000,
              layers=(100, 256, 47)),
    # 8: APPNP at the arxiv shape (beyond reference) — k teleport-
    # anchored propagation hops over the trainer's resolved layout;
    # the hop loop is GCN's hot path with a fused lerp, so epoch time
    # ~ k/2 x the 2-hop SAGE row above plus the (cheap) MLP
    "8": dict(model="appnp", nodes=169_343, edges=4_600_000,
              layers=(128, 256, 40)),
    # 9: GCNII at the arxiv shape, 8 propagation layers (beyond
    # reference) — the deep-stack family; per layer one aggregation +
    # one [V, 256] matmul, so ~4x the 2-hop SAGE row's aggregation
    # count
    "9": dict(model="gcn2",
              nodes=169_343, edges=4_600_000,
              layers=(128,) + (256,) * 8 + (40,)),
}
_OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "model_zoo.jsonl")


def run(cfg_key: str, epochs: int, impl: str,
        dtype: str = "float32", heads: int = 1,
        remat: bool = False) -> dict:
    import jax
    from roc_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache()
    from roc_tpu.core.graph import Dataset, random_csr
    from roc_tpu.models.gat import build_gat
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.models.appnp import build_appnp
    from roc_tpu.models.gcn2 import build_gcn2
    from roc_tpu.models.gin import build_gin
    from roc_tpu.models.sage import build_sage
    from roc_tpu.train.trainer import TrainConfig, Trainer

    c = CONFIGS[cfg_key]
    layers = list(c["layers"])
    # validate BEFORE the minutes-long synthetic graph generation
    # (same policy as roc_tpu/train/cli.py's up-front flag checks)
    if heads != 1:
        if c["model"] != "gat":
            raise SystemExit(
                f"--heads applies to gat configs only; config "
                f"{cfg_key} is {c['model']}")
        if heads < 1 or any(d % heads for d in layers[1:-1]):
            raise SystemExit(
                f"--heads {heads} invalid for hidden dims {layers[1:-1]}")
    if impl == "auto" and c["model"] != "gat":
        # record the kernel that actually runs, not the CLI alias.
        # GAT configs keep 'auto': the TRAINER's resolver owns the
        # attention routing (ell below ATTN_FLAT8_MIN_EDGES, the
        # uniform flat8 layout above it — it needs the dataset, which
        # this early resolution doesn't have)
        # num_edges arms the flat_sum compile-wall route past the
        # sectioned window (core/ell.py FLAT_SUM_MIN_EDGES) — the
        # products-scale zoo configs are exactly its target
        from roc_tpu.core.ell import resolve_auto_impl
        impl = resolve_auto_impl(c["nodes"], num_edges=c["edges"])
    dev = jax.devices()[0]
    print(f"# config {cfg_key}: {c['model']} V={c['nodes']} "
          f"E={c['edges']} on {dev.device_kind}", file=sys.stderr)
    t0 = time.time()
    graph = random_csr(c["nodes"], c["edges"], seed=0)
    rng = np.random.RandomState(1)
    ds = Dataset(
        graph=graph,
        features=rng.rand(c["nodes"], layers[0]).astype(np.float32),
        labels=rng.randint(0, layers[-1],
                           size=c["nodes"]).astype(np.int32),
        mask=rng.choice([1, 2, 3], size=c["nodes"],
                        p=[0.66, 0.10, 0.24]).astype(np.int32),
        num_classes=layers[-1], name=f"config{cfg_key}-synth")
    print(f"# data gen {time.time()-t0:.0f}s", file=sys.stderr)

    build = {"gcn": build_gcn, "sage": build_sage, "gin": build_gin,
             "gat": build_gat, "appnp": build_appnp,
             "gcn2": build_gcn2}
    kwargs = {"heads": heads} if c["model"] == "gat" else {}
    if c["model"] == "appnp":
        kwargs["k"] = 10  # the paper's classic depth (cli.py default)
    model = build[c["model"]](layers, dropout_rate=0.5, **kwargs)
    # GIN aggregates raw F-wide features (dropout output feeds
    # scatter_gather directly), which the ELL-family impls handle;
    # 'auto' resolves per the measured window (ell at products scale,
    # sectioned at arxiv scale — core/ell.py resolve_auto_impl)
    # memory="auto": the products/Amazon shapes exceed HBM without
    # remat — the autopilot estimates and picks (echoed on stderr)
    # dtype="mixed" = fp32 master params + bf16 compute: at products/
    # Amazon scale this is what makes GIN fit (fp32 + remat still OOMs
    # a 16G chip by ~0.4G) and halves aggregation HBM traffic
    from roc_tpu.train.trainer import resolve_dtypes
    dt, cdt = resolve_dtypes(dtype)
    # --remat forces manual remat (the autopilot's estimator doesn't
    # model attention's extra transients; config 7 needs this)
    tc = TrainConfig(learning_rate=0.01, weight_decay=1e-4,
                     aggr_impl=impl, verbose=True,
                     dtype=dt, compute_dtype=cdt,
                     eval_every=1 << 30, symmetric=True,
                     memory="manual" if remat else "auto",
                     remat=remat)
    t0 = time.time()
    tr = Trainer(model, ds, tc)
    tr.train(epochs=2)
    tr.sync()
    compile_s = time.time() - t0
    print(f"# prep+compile+warmup {compile_s:.0f}s", file=sys.stderr)
    times = []
    for _ in range(epochs):
        t0 = time.time()
        tr.train(epochs=1)
        tr.sync()
        times.append((time.time() - t0) * 1e3)
    rec = {"config": cfg_key, "model": c["model"], "V": c["nodes"],
           "E": int(graph.num_edges), "layers": layers,
           # the trainer's resolved impl, not the CLI alias — e.g.
           # attention models override to 'ell' at setup
           "impl": tr.config.aggr_impl,
           "dtype": dtype,
           **({"heads": heads} if c["model"] == "gat" and heads != 1
              else {}),
           **({"remat": True} if remat else {}),
           "platform": dev.platform, "device_kind": dev.device_kind,
           "epoch_ms": round(float(np.median(times)), 1),
           "epoch_ms_all": [round(t) for t in times],
           "compile_s": round(compile_s, 1),
           "recorded": time.strftime("%Y-%m-%dT%H:%M:%S%z")}
    print(f"# epochs (ms): {rec['epoch_ms_all']}", file=sys.stderr)
    with open(_OUT, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec))
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="3",
                    choices=list(CONFIGS))
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--impl", default="auto")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16", "mixed"])
    ap.add_argument("--heads", type=int, default=1,
                    help="attention heads (gat configs only)")
    ap.add_argument("--remat", action="store_true",
                    help="force remat (skip the memory autopilot)")
    args = ap.parse_args()
    run(args.config, args.epochs, args.impl, args.dtype, args.heads,
        args.remat)


if __name__ == "__main__":
    main()
