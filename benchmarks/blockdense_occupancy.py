"""Block-dense occupancy census: how much of a graph's edge mass can
ride [128,128] MXU tiles under a given vertex order.

Host-side only (no accelerator): the stat that decides whether
``aggr_impl='bdense'`` can beat the ~7 ns/edge gather row-rate
(BASELINE.md "Round-5 additions").  Substrate spec mirrors
micro_agg's ``--graph``, plus an optional reorder pass so the
ordering-recovery claim (core/reorder.py lpa_order) is measurable at
any scale with one command:

    python benchmarks/blockdense_occupancy.py \
        --nodes 232965 --edges 114848857 \
        --graph planted:16384 --reorder lpa

Merges the row into benchmarks/blockdense_occupancy.json under a key
derived from the spec (here ``planted16384_lpa``) so re-running the
recorded command updates the recorded row rather than forking a new
one; ``--tag`` overrides the key.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "blockdense_occupancy.json")


def main():
    from _substrates import GRAPH_SPEC_HELP, graph_from_spec, \
        reorder_graph
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=232_965)
    ap.add_argument("--edges", type=int, default=114_848_857)
    ap.add_argument("--graph", default="planted:16384",
                    help=GRAPH_SPEC_HELP)
    ap.add_argument("--reorder", default="none",
                    choices=["none", "bfs", "lpa"])
    ap.add_argument("--min-fill", type=int, default=64)
    ap.add_argument("--a-budget", type=int, default=2 << 30,
                    help="uint8 A-table byte cap (0 = uncapped, same "
                         "convention as micro_agg.py --a-budget)")
    ap.add_argument("--group", type=int, default=1,
                    help="pad_plan_groups alignment (the grouped "
                         "output-tile reduction); occupancy then "
                         "reports pad_blocks and the padded a_bytes")
    ap.add_argument("--pack", action="store_true",
                    help="apply the trainer's plan_blocks_packed "
                         "policy (u4 packing + 2x-budget planning) "
                         "instead of the raw uint8 plan")
    ap.add_argument("--tag", default=None,
                    help="JSON key (default: derived from the spec)")
    args = ap.parse_args()

    t0 = time.time()
    g = graph_from_spec(args.graph, args.nodes, args.edges)
    gen_s = time.time() - t0

    g, reorder_s = reorder_graph(
        g, args.reorder,
        cache_key=f"{args.graph}_{args.nodes}_{args.edges}")
    if reorder_s:
        print(f"# {args.reorder} reorder: {reorder_s:.1f}s")

    from roc_tpu.ops.blockdense import (BLOCK, plan_blocks,
                                        plan_blocks_packed)
    t0 = time.time()
    planner = plan_blocks_packed if args.pack else plan_blocks
    plan = planner(g.row_ptr, g.col_idx, g.num_nodes,
                   min_fill=args.min_fill,
                   a_budget_bytes=args.a_budget or None,
                   group=args.group)
    plan_s = time.time() - t0

    row = dict(plan.occupancy(), V=g.num_nodes, E=g.num_edges,
               min_fill=args.min_fill, gen_s=round(gen_s, 1),
               plan_s=round(plan_s, 1),
               graph=args.graph,
               reorder=args.reorder,
               reorder_s=round(reorder_s, 1))
    if args.group > 1:
        row["group"] = args.group
    if args.pack:
        row["a_u4"] = bool(plan.a_blocks.shape[-1] == BLOCK // 2)
    # non-default plan knobs join the derived key: rows measured under
    # different min_fill/a_budget must never overwrite each other
    tag = args.tag or (args.graph.replace(":", "")
                       + ("" if args.reorder == "none"
                          else f"_{args.reorder}")
                       + ("" if args.min_fill == 64
                          else f"_f{args.min_fill}")
                       + ("" if args.a_budget == 2 << 30
                          else "_bunc" if not args.a_budget
                          else f"_b{args.a_budget >> 30}g")
                       + ("" if args.group == 1 else f"_g{args.group}")
                       # suffix by the packing OUTCOME, not the knob:
                       # an unpackable graph records as '_pack' (with
                       # a_u4: false), never as a phantom u4 row
                       + ("" if not args.pack
                          else "_u4" if row["a_u4"] else "_pack"))
    print(tag, json.dumps(row, sort_keys=True))

    data = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            data = json.load(f)
    data[tag] = row
    with open(OUT, "w") as f:
        json.dump(data, f, indent=1, sort_keys=True)


if __name__ == "__main__":
    main()
