#!/usr/bin/env python
"""Micro-benchmark: greedy edge sweep vs cost-balanced partitioning.

The SPMD layer pads every shard to the MAX shard's quantized shapes,
so the straggler's padded program gates every step and every ring hop
— the split IS the cost.  Three probes per (substrate, P):

1. **split race** (host): greedy (``edge_balanced_bounds``, the
   reference ``gnn.cc:806-829`` sweep) vs cost
   (``costmodel.cost_balanced_bounds`` minimax search) — modeled
   max-shard cost, padded part shapes, edge imbalance, split wall ms.
2. **max-shard step race** (device): the straggler's padded
   aggregation program under each split — a gather + segment-sum over
   ``part_edges`` padded edges into ``part_nodes`` rows, i.e. exactly
   the per-device shape the distributed step compiles.  The cost split
   must reduce this measured time, not just the model's number.
3. **distributed epoch race** (when the backend has >= P devices):
   short GCN training runs with ``partition='greedy'`` vs ``'cost'``,
   median steady epoch_ms.

Substrates: ``zipf[:A]`` power-law in-degrees (the acceptance
substrate — Zipf hubs are the edge-balanced sweep's worst case) and
the Reddit-shaped ``planted`` community graph.

Usage: python benchmarks/micro_partition.py [--cpu] [--out out.json]
The CPU rehearsal artifact lives at benchmarks/micro_partition_cpu.json;
chip numbers queue through scripts/round6_chain.sh.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from _substrates import GRAPH_SPEC_HELP, graph_from_spec  # noqa: E402


def bench(fn, iters=10):
    """Median wall ms with the fetch-based barrier (micro_agg.py)."""
    import jax.numpy as jnp
    out = fn()
    float(jnp.sum(out))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn()
        float(jnp.sum(out))
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times))


def split_row(g, parts, method, weights, nm, em):
    """Host-side split + modeled stats for one method."""
    from roc_tpu.core.costmodel import bounds_max_cost
    from roc_tpu.core.partition import partition_bounds, partition_plan
    t0 = time.perf_counter()
    bounds = partition_bounds(g.row_ptr, parts, method=method,
                              node_multiple=nm, edge_multiple=em,
                              cost_weights=weights)
    split_ms = (time.perf_counter() - t0) * 1e3
    plan = partition_plan(g.row_ptr, parts, node_multiple=nm,
                          edge_multiple=em, method=method,
                          cost_weights=weights)
    re = np.asarray(plan.real_edges, dtype=np.float64)
    return plan, {
        "split_ms": round(split_ms, 2),
        "modeled_max_cost": round(float(bounds_max_cost(
            g.row_ptr, bounds, weights[0], weights[1], nm, em)), 7),
        "part_nodes": int(plan.part_nodes),
        "part_edges": int(plan.part_edges),
        "max_real_edges": int(re.max()),
        "edge_imbalance": round(float(re.max() / max(re.mean(), 1)),
                                4),
    }


def shard_step_ms(g, plan, F, iters):
    """Measured straggler step: the padded per-device aggregation
    program this split compiles — [part_edges] gather + sorted
    segment-sum into [part_nodes] rows (dummy source = the appended
    zero row, exactly the trainers' convention)."""
    import jax
    import jax.numpy as jnp
    from roc_tpu.core.partition import materialize_plan
    from roc_tpu.ops.aggregate import aggregate
    pg = materialize_plan(g, plan)
    p = int(np.argmax(pg.real_edges))
    src = jnp.asarray(pg.part_col_idx[p])          # [part_edges]
    dst = jnp.asarray(np.repeat(
        np.arange(pg.part_nodes, dtype=np.int32),
        np.diff(pg.part_row_ptr[p])))
    x = np.random.RandomState(0).rand(
        g.num_nodes + 1, F).astype(np.float32)
    x[-1] = 0
    xj = jnp.asarray(x)
    f = jax.jit(lambda xx: aggregate(xx, src, dst, pg.part_nodes,
                                     impl="segment"))
    return bench(lambda: f(xj), iters)


def epoch_race(g, parts, epochs):
    """Distributed GCN epochs per partition method (>= P devices)."""
    import jax
    if len(jax.devices()) < parts:
        return {"skipped": f"{len(jax.devices())} device(s)"}
    from roc_tpu.core.graph import MASK_NONE, Dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.parallel.distributed import DistributedTrainer
    from roc_tpu.train.trainer import TrainConfig
    rng = np.random.RandomState(1)
    V, F, C = g.num_nodes, 64, 8
    ds = Dataset(graph=g,
                 features=rng.rand(V, F).astype(np.float32),
                 labels=rng.randint(0, C, size=V).astype(np.int32),
                 mask=np.full(V, MASK_NONE, dtype=np.int32),
                 num_classes=C, name="micro_partition")
    ds.mask[rng.rand(V) < 0.5] = 1
    rows = {}
    for method in ("greedy", "cost"):
        cfg = TrainConfig(verbose=False, symmetric=True,
                          dropout_rate=0.0, partition=method,
                          eval_every=1 << 30, epochs=epochs)
        tr = DistributedTrainer(build_gcn([F, 32, C],
                                          dropout_rate=0.0),
                                ds, parts, cfg)
        tr.train(epochs=2)   # compile + warmup
        tr.sync()
        times = []
        for _ in range(epochs):
            t0 = time.perf_counter()
            tr.train(epochs=1)
            tr.sync()
            times.append((time.perf_counter() - t0) * 1e3)
        rows[method] = {"epoch_ms": round(float(np.median(times)), 2),
                        "part_edges": int(tr.pg.part_edges)}
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=131_072)
    ap.add_argument("--edges", type=int, default=2_621_440)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--graphs", type=str,
                    default="zipf:1.2,planted:16384",
                    help=f"comma list of substrates: {GRAPH_SPEC_HELP}")
    ap.add_argument("--parts", type=str, default="4,8",
                    help="comma list of shard counts")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--edge-multiple", type=int, default=512)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--out", type=str, default=None)
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    print(f"# device={dev.platform} {dev.device_kind} "
          f"V={args.nodes} E={args.edges}", file=sys.stderr)

    # cold-start weights: the edge-balance prior the trainers use
    from roc_tpu.core.costmodel import PartitionCostModel
    weights = PartitionCostModel().search_weights()
    nm, em = 8, args.edge_multiple

    result = {"device": f"{dev.platform} {dev.device_kind}",
              "config": vars(args) | {"weights": list(weights)},
              "races": {}}
    wins = []
    for spec in args.graphs.split(","):
        g = graph_from_spec(spec, args.nodes, args.edges)
        for parts in (int(p) for p in args.parts.split(",")):
            row = {}
            plans = {}
            for method in ("greedy", "cost"):
                plans[method], row[method] = split_row(
                    g, parts, method, weights, nm, em)
                row[method]["shard_step_ms"] = round(shard_step_ms(
                    g, plans[method], args.dim, args.iters), 3)
            row["epochs"] = epoch_race(g, parts, args.epochs)
            win = {
                "modeled_reduced": bool(
                    row["cost"]["modeled_max_cost"]
                    <= row["greedy"]["modeled_max_cost"]),
                "measured_reduced": bool(
                    row["cost"]["shard_step_ms"]
                    <= row["greedy"]["shard_step_ms"]),
                "part_edges_ratio": round(
                    row["cost"]["part_edges"]
                    / max(row["greedy"]["part_edges"], 1), 4),
            }
            row["win"] = win
            wins.append(win)
            result["races"][f"{spec}/P{parts}"] = row
            print(f"# {spec} P={parts}: part_edges "
                  f"{row['greedy']['part_edges']} -> "
                  f"{row['cost']['part_edges']} "
                  f"({win['part_edges_ratio']:.2f}x), shard step "
                  f"{row['greedy']['shard_step_ms']} -> "
                  f"{row['cost']['shard_step_ms']} ms",
                  file=sys.stderr)
    result["win"] = {
        "modeled_reduced_all": bool(all(w["modeled_reduced"]
                                        for w in wins)),
        "measured_reduced_any": bool(any(w["measured_reduced"]
                                         for w in wins)),
    }
    line = json.dumps(result, indent=1)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
