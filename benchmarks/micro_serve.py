#!/usr/bin/env python
"""Micro-benchmark: serving-tier load generator — QPS + latency
percentiles for the ``roc_tpu/serve`` inference backends.

Builds the SGC serving rig (synthetic graph, frozen Glorot weights —
serving latency is weight-independent), exports BOTH backends through
the real artifact path (``serve/export.py``: resolve → propagation
precompute → AOT prewarm → manifest), then drives a ``Server`` with
two canonical traffic shapes:

1. **closed-loop** — one outstanding query at a time (per client):
   the p50 here is pure request latency, the number the ISSUE's
   "precomputed ≥10× lower p50 than full-graph predict" acceptance is
   measured on;
2. **open-loop Poisson** — arrivals at a fixed rate λ drawn from an
   exponential inter-arrival clock, submitted without waiting for
   completions (the shape real traffic has; p99 under this load shows
   the coalescing queue absorbing bursts instead of head-of-line
   blocking on them).

Reported per backend: p50/p99 request latency (submit→result), QPS
(completed/wall), and the server's microbatch stats.  The headline
speedup row divides full-graph p50 by precomputed p50 — the measured
form of "the fixed-propagation family collapses at serving time".

Closed-loop rows also decompose server-side latency into
``queue_p50_ms`` (admission → dispatch) vs ``device_p50_ms`` (the
microbatch's device wall) from the PR-17 ``ServeResult`` stamps, and
the ``precomputed_noobs`` row re-runs the same load with
``instrument=False`` — the observability-overhead A/B the "registry +
tracing within 5% of instrumentation-off" acceptance reads
(``obs_overhead_pct``).  ``--slo-smoke`` runs ONLY the CI serving
gate: export → cold-load behind a 2-replica Router with declared
SLOs → quiet load-gen → exit 0 iff ``Router.health()`` is green.

The quantized-serving pair (PR 19): the ``precomputed_q8`` row
re-exports the precomputed backend with ``--quantize int8`` and the
``quant_ab`` summary pairs it with the fp32 row — artifact table
bytes (the ≥3× shrink acceptance), p50/p99/QPS, and the export drift
gate's argmax/|Δlogit| measurements (``serve_table_bytes`` /
``serve_quant_drift`` sentinel columns).  ``--quant-smoke`` runs ONLY
the PR-19 CI gate: export int8 (drift gate must pass) → cold-load →
load-gen → served answers bit-equal to the gated values, exit 1
otherwise.

Usage: python benchmarks/micro_serve.py [--cpu] [--queries N]
       [--rate QPS|auto] [--out out.json]
The CPU rehearsal artifact lives at benchmarks/micro_serve_cpu.json;
``bench.py``'s ``serve`` stage runs the same harness on the chip and
feeds ``serve_p50_ms``/``serve_p99_ms``/``serve_qps`` into the
BENCH_* headline (gated by ``python -m roc_tpu.sentinel``).
"""

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def build_rig(nodes, degree, feat, classes, hops, seed=0):
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.sgc import build_sgc
    from roc_tpu.train.trainer import TrainConfig
    ds = synthetic_dataset(num_nodes=nodes, avg_degree=degree,
                           in_dim=feat, num_classes=classes, seed=seed)
    model = build_sgc([feat, classes], k=hops, dropout_rate=0.5)
    cfg = TrainConfig(verbose=False, symmetric=True)
    return ds, model, cfg


def _pcts(lat_ms):
    lat = sorted(lat_ms)

    def pct(p):
        return round(lat[min(len(lat) - 1, int(p * len(lat)))], 4)

    return {"p50_ms": pct(0.50), "p99_ms": pct(0.99),
            "mean_ms": round(float(np.mean(lat)), 4)}


def closed_loop(server, ids_seq):
    """One outstanding query at a time; returns latency list + wall
    + the server-side queue/device decomposition (``ServeResult``
    stamps ``queue_ms``/``device_ms`` per request — queue-depth
    pressure vs device wall, the PR-17 latency breakdown;
    ``instrument=False`` servers stamp None and the lists come back
    empty)."""
    lat, queue_ms, device_ms = [], [], []
    t_start = time.perf_counter()
    for ids in ids_seq:
        t0 = time.perf_counter()
        res = server.query(ids)
        lat.append((time.perf_counter() - t0) * 1e3)
        q = getattr(res, "queue_ms", None)
        d = getattr(res, "device_ms", None)
        if q is not None:
            queue_ms.append(q)
        if d is not None:
            device_ms.append(d)
    return lat, time.perf_counter() - t_start, queue_ms, device_ms


def open_loop(server, ids_seq, rate_qps, seed=0):
    """Poisson arrivals at ``rate_qps``; submissions never wait for
    completions, so queueing delay is part of the measured latency
    (the honest open-loop convention)."""
    rng = np.random.RandomState(seed)
    gaps = rng.exponential(1.0 / max(rate_qps, 1e-6),
                           size=len(ids_seq))
    done_at = {}

    def _stamp(i):
        # done-callbacks run in the dispatcher thread the moment the
        # future resolves — per-request completion stamps stay honest
        # even when the submitting loop is behind
        def cb(_fut):
            done_at[i] = time.perf_counter()
        return cb

    pending = []
    t_start = time.perf_counter()
    t_next = t_start
    for i, (ids, gap) in enumerate(zip(ids_seq, gaps)):
        t_next += gap
        now = time.perf_counter()
        if t_next > now:
            time.sleep(t_next - now)
        t0 = time.perf_counter()
        fut = server.submit(ids)
        fut.add_done_callback(_stamp(i))
        pending.append((i, t0, fut))
    for _, _, fut in pending:
        fut.result()
    wall = time.perf_counter() - t_start
    # result() can return BEFORE the done-callback ran (set_result
    # wakes waiters first, then invokes callbacks) — give the
    # dispatcher thread a beat to finish stamping
    deadline = time.perf_counter() + 5.0
    while len(done_at) < len(pending) and time.perf_counter() < deadline:
        time.sleep(0.0005)
    t_fallback = time.perf_counter()
    lat = [(done_at.get(i, t_fallback) - t0) * 1e3
           for i, t0, _ in pending]
    return lat, wall


def run_backend(backend, ds, model, cfg, queries, batch, rate,
                art_root, seed=0, max_wait_ms=0.2, instrument=True,
                quant="off"):
    """Export one backend through the real artifact path, then drive
    closed- and open-loop traffic against a cold-loaded server.
    ``instrument=False`` runs the same load with registry recording
    and trace stamping disarmed — the A/B row the observability-
    overhead acceptance (steady-state p50 within 5%) is measured on.
    ``quant='int8'`` exports quantized serving tables (PR 19) — the
    row additionally carries the artifact's table bytes and the
    export drift gate's measurements, the quant:off/quant:int8 A/B
    pair the headline mines."""
    from roc_tpu.serve.export import (build_predictor, export_predictor,
                                      load_predictor)
    from roc_tpu.serve.server import Server
    out_dir = os.path.join(
        art_root, backend + ("" if quant == "off" else f"_{quant}"))
    t0 = time.perf_counter()
    pred = build_predictor(model, ds, cfg, backend=backend,
                           quant=quant)
    manifest = export_predictor(
        pred, out_dir,
        dataset_meta={"V": ds.graph.num_nodes,
                      "E": ds.graph.num_edges})
    export_s = time.perf_counter() - t0
    # the measured server is a COLD load of the artifact — the path a
    # real deployment takes (the export process's jits are not reused)
    t0 = time.perf_counter()
    pred = load_predictor(
        out_dir, dataset=ds if backend == "full" else None)
    warm = pred.warm(name=f"serve_bench_{backend}_{quant}")
    load_s = time.perf_counter() - t0
    rng = np.random.RandomState(seed)
    ids_seq = [rng.randint(0, ds.graph.num_nodes,
                           size=batch).astype(np.int32)
               for _ in range(queries)]
    row = {"backend": backend, "flavor": manifest["flavor"],
           "quant": quant,
           "instrument": bool(instrument),
           "export_s": round(export_s, 2),
           "cold_load_s": round(load_s, 3),
           "warm_hits": warm.get("compile_warm_hits"),
           "cold_compiles": warm.get("compile_cold")}
    # quantized-serving columns (PR 19): the artifact's propagation
    # table bytes (fp32 rows see shrink 1.0) and, for quantized
    # exports, the gate's measured drift — these feed the
    # serve_table_bytes / serve_quant_drift sentinel columns
    qb = manifest.get("quant") or {}
    table = qb.get("table") or {}
    if table.get("bytes") is not None:
        row["table_bytes"] = table["bytes"]
        row["table_bytes_fp32"] = table.get("bytes_fp32")
        row["table_shrink"] = table.get("shrink")
    drift = qb.get("drift")
    if drift is not None:
        row["argmax_drift"] = round(
            1.0 - drift["argmax_agreement"], 4)
        row["quant_drift"] = drift["rel_dlogit"]
    with Server(pred, max_wait_ms=max_wait_ms,
                instrument=instrument) as srv:
        # closed loop first — its throughput calibrates 'auto' rate
        lat, wall, qms, dms = closed_loop(srv, ids_seq)
        closed = _pcts(lat)
        closed["qps"] = round(len(lat) / max(wall, 1e-9), 1)
        # queue-delay vs device-time decomposition: where a request's
        # server-side milliseconds actually went
        if qms:
            closed["queue_p50_ms"] = _pcts(qms)["p50_ms"]
        if dms:
            closed["device_p50_ms"] = _pcts(dms)["p50_ms"]
        row["closed"] = closed
        eff_rate = (0.5 * closed["qps"] if rate == "auto"
                    else float(rate))
        lat, wall = open_loop(srv, ids_seq, eff_rate, seed=seed)
        opened = _pcts(lat)
        opened["qps"] = round(len(lat) / max(wall, 1e-9), 1)
        opened["offered_qps"] = round(eff_rate, 1)
        row["open"] = opened
        row["server"] = srv.stats()
    return row


def run_obs_ab(pred, ds, queries, batch, max_wait_ms,
               trials=3, seed=0):
    """Observability-overhead A/B (the 'steady-state p50 within 5%'
    acceptance): alternate instrumented / disarmed closed-loop passes
    over the SAME loaded predictor and compare median-of-trials p50s.
    A single pair is dominated by scheduler jitter at sub-ms request
    latencies (observed ±30% between identical runs); interleaving
    the arms and taking medians cancels the machine drift that a
    sequential pair bakes into one arm."""
    from roc_tpu.serve.server import Server
    rng = np.random.RandomState(seed)
    ids_seq = [rng.randint(0, ds.graph.num_nodes,
                           size=batch).astype(np.int32)
               for _ in range(queries)]
    p50s = {True: [], False: []}
    for trial in range(trials):
        order = (True, False) if trial % 2 == 0 else (False, True)
        for inst in order:
            with Server(pred, max_wait_ms=max_wait_ms,
                        instrument=inst) as srv:
                lat, _, _, _ = closed_loop(srv, ids_seq)
            p50s[inst].append(_pcts(lat)["p50_ms"])
    def _med(vs):
        vs = sorted(vs)
        n = len(vs)
        return vs[n // 2] if n % 2 else 0.5 * (vs[n // 2 - 1]
                                               + vs[n // 2])
    on, off = _med(p50s[True]), _med(p50s[False])
    return {"trials": trials, "queries_per_pass": queries,
            "p50_on_ms": round(on, 4), "p50_off_ms": round(off, 4),
            "p50_on_all": [round(v, 4) for v in p50s[True]],
            "p50_off_all": [round(v, 4) for v in p50s[False]],
            "overhead_pct": round(100.0 * (on - off)
                                  / max(off, 1e-9), 1)}


def run_slo_smoke(ds, model, cfg, art_root, queries=100,
                  n_replicas=2, batch=4, seed=0):
    """The SLO smoke (PR 17 CI gate): export the precomputed backend,
    cold-load it behind a Router with declared objectives, drive a
    quiet load-gen pass, and require ``Router.health()`` green —
    availability 1.0 and every burn rate in-state.  Exit-enforced by
    scripts/test.sh preflight and round6_chain step 0b: a serving
    tier that cannot pass a quiet smoke has no business in a round."""
    from roc_tpu.serve.export import build_predictor, export_predictor
    from roc_tpu.serve.router import Router
    out_dir = os.path.join(art_root, "slo_smoke")
    pred = build_predictor(model, ds, cfg, backend="precomputed")
    export_predictor(pred, out_dir,
                     dataset_meta={"V": ds.graph.num_nodes,
                                   "E": ds.graph.num_edges})
    rng = np.random.RandomState(seed)
    ids_seq = [rng.randint(0, ds.graph.num_nodes,
                           size=batch).astype(np.int32)
               for _ in range(queries)]
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("ROC_TPU_FAULT", None)   # a smoke is quiet by definition
    slos = ("availability(ok/requests) >= 0.99 over 30s",
            "latency_p99: p99(request_ms) <= 2000ms over 30s")
    # a genuine breach during the smoke must not litter the caller's
    # cwd with flight records — dumps land next to the artifact
    prev_flight = os.environ.get("ROC_TPU_FLIGHT_DIR")
    os.environ["ROC_TPU_FLIGHT_DIR"] = out_dir
    t0 = time.perf_counter()
    try:
        with Router(out_dir, n_replicas=n_replicas, cpu=True, env=env,
                    default_deadline_ms=30_000.0, slos=slos) as router:
            futs = [router.submit(ids) for ids in ids_seq]
            for f in futs:
                f.result(timeout=60)
            health = router.health()
            stats = router.stats()
    finally:
        if prev_flight is None:
            os.environ.pop("ROC_TPU_FLIGHT_DIR", None)
        else:
            os.environ["ROC_TPU_FLIGHT_DIR"] = prev_flight
    return {"queries": queries, "replicas": n_replicas,
            "ok": bool(health.get("ok")),
            "availability": stats.get("availability"),
            "p99_ms": stats.get("p99_ms"),
            "wall_s": round(time.perf_counter() - t0, 2),
            "health": health}


def run_quant_ab(pred_off, pred_q8, ds, queries, batch,
                 max_wait_ms, trials=4, seed=0):
    """Paired interleaved p50 A/B between the fp32 and int8 loaded
    predictors — the ``run_obs_ab`` precedent: at sub-ms request
    latencies two sequential rows disagree by ±30% on machine drift
    alone, so the 'int8 p50 no worse than fp32' acceptance is
    measured on interleaved arms and median-of-trials, not on the
    independent backend rows."""
    from roc_tpu.serve.server import Server
    rng = np.random.RandomState(seed)
    ids_seq = [rng.randint(0, ds.graph.num_nodes,
                           size=batch).astype(np.int32)
               for _ in range(queries)]
    p50s = {"off": [], "int8": []}
    arms = {"off": pred_off, "int8": pred_q8}
    for trial in range(trials):
        order = (("off", "int8") if trial % 2 == 0
                 else ("int8", "off"))
        for name in order:
            with Server(arms[name], max_wait_ms=max_wait_ms) as srv:
                lat, _, _, _ = closed_loop(srv, ids_seq)
            p50s[name].append(_pcts(lat)["p50_ms"])
    def _med(vs):
        vs = sorted(vs)
        n = len(vs)
        return vs[n // 2] if n % 2 else 0.5 * (vs[n // 2 - 1]
                                               + vs[n // 2])
    off, q8 = _med(p50s["off"]), _med(p50s["int8"])
    return {"trials": trials, "queries_per_pass": queries,
            "p50_off_ms": round(off, 4), "p50_int8_ms": round(q8, 4),
            "p50_off_all": [round(v, 4) for v in p50s["off"]],
            "p50_int8_all": [round(v, 4) for v in p50s["int8"]],
            "delta_pct": round(100.0 * (q8 - off)
                               / max(off, 1e-9), 1)}


def run_quant_smoke(ds, model, cfg, art_root, queries=100,
                    batch=4, mode="int8", seed=0):
    """The quantized-serving smoke (PR 19 CI gate): export the
    precomputed backend at ``mode`` — the export-side drift gate must
    pass (export REFUSES past threshold) — then cold-load the
    artifact, drive a quiet load-gen pass through a Server, and
    require every served answer to match the export-process
    predictor's gated values bit-exactly (the round-trip identity:
    quantize∘dequantize∘quantize is lossless, so a cold load
    reconstructs the same device codes).  Exit-enforced by
    scripts/test.sh preflight and round6_chain step 0b: a quantized
    artifact that drifts past the gate, or a cold load that serves
    different values than were gated, never reaches a round."""
    from roc_tpu.serve.export import (build_predictor, export_predictor,
                                      load_predictor)
    from roc_tpu.serve.quant import QuantDriftError
    from roc_tpu.serve.server import Server
    out_dir = os.path.join(art_root, "quant_smoke")
    t_start = time.perf_counter()
    pred = build_predictor(model, ds, cfg, backend="precomputed",
                           quant=mode)
    try:
        manifest = export_predictor(
            pred, out_dir,
            dataset_meta={"V": ds.graph.num_nodes,
                          "E": ds.graph.num_edges})
    except QuantDriftError as e:
        return {"mode": mode, "queries": queries, "ok": False,
                "stage": "export-gate", "error": str(e)}
    qb = manifest["quant"]
    drift = qb["drift"]
    table = qb.get("table") or {}
    rng = np.random.RandomState(seed)
    ids_seq = [rng.randint(0, ds.graph.num_nodes,
                           size=batch).astype(np.int32)
               for _ in range(queries)]
    # reference answers from the export-process predictor — already
    # the gated dequantize∘quantize values the artifact persists
    want = [np.asarray(pred.query(ids)) for ids in ids_seq]
    cold = load_predictor(out_dir)
    wrong = 0
    qmodes = set()
    lat = []
    with Server(cold, max_wait_ms=0.2) as srv:
        for ids, ref in zip(ids_seq, want):
            t0 = time.perf_counter()
            res = srv.query(ids)
            lat.append((time.perf_counter() - t0) * 1e3)
            qmodes.add(getattr(res, "qmode", None))
            if np.abs(np.asarray(res) - ref).max() > 0.0:
                wrong += 1
    ok = (bool(drift.get("ok")) and wrong == 0
          and cold.quant == mode and qmodes == {mode})
    row = {"mode": mode, "queries": queries, "ok": ok,
           "wrong": wrong, "qmode_served": sorted(
               str(m) for m in qmodes),
           "loaded_quant": cold.quant,
           "export_drift": drift,
           "table_bytes": table.get("bytes"),
           "table_shrink": table.get("shrink"),
           "wall_s": round(time.perf_counter() - t_start, 2)}
    row.update(_pcts(lat))
    return row


def run_router_drill(ds, model, cfg, art_root, queries=120,
                     n_replicas=2, kill_batch=4, batch=4,
                     deadline_ms=10_000.0, seed=0):
    """Kill-a-replica load generation (ISSUE 13 acceptance): export
    the precomputed backend, front it with a 2-replica Router, arm
    ``replica_sigkill:<kill_batch>:1`` so replica 1 SIGKILLs itself
    mid-load, and drive queries through the kill.  Every accepted
    request must complete with a correct answer or a typed
    deadline/shed failure — ``wrong`` (answers off by >1e-5 from the
    reference) must be ZERO; failover/hedge counts and the
    availability triple are the row.

    Replicas always run on CPU: this scenario measures AVAILABILITY
    under fault, not device latency (N replicas racing one single-
    claim TPU tunnel would drill the tunnel, not the router), and
    correctness/failover behavior is platform-independent.  The
    latency rows stay with the single-process backends above."""
    from roc_tpu.serve.errors import ServeOverload, ServeTimeout
    from roc_tpu.serve.export import build_predictor, export_predictor
    from roc_tpu.serve.router import Router
    out_dir = os.path.join(art_root, "router")
    pred = build_predictor(model, ds, cfg, backend="precomputed")
    export_predictor(pred, out_dir,
                     dataset_meta={"V": ds.graph.num_nodes,
                                   "E": ds.graph.num_edges})
    rng = np.random.RandomState(seed)
    ids_seq = [rng.randint(0, ds.graph.num_nodes,
                           size=batch).astype(np.int32)
               for _ in range(queries)]
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env["ROC_TPU_FAULT"] = f"replica_sigkill:{kill_batch}:1"
    ok = wrong = timeout = shed = other = 0
    lat = []
    got: dict = {}
    t_start = time.perf_counter()
    with Router(out_dir, n_replicas=n_replicas, cpu=True, env=env,
                default_deadline_ms=deadline_ms) as router:
        futs = []
        for i, ids in enumerate(ids_seq):
            futs.append((i, time.perf_counter(), router.submit(ids)))
            time.sleep(0.002)   # open-ish: keep both replicas busy
        for i, t0, fut in futs:
            try:
                got[i] = np.asarray(fut.result(timeout=60))
                lat.append((time.perf_counter() - t0) * 1e3)
            except ServeTimeout:
                timeout += 1
            except ServeOverload:
                shed += 1
            except Exception:  # noqa: BLE001 - anything else is a bug
                other += 1
        # correctness reference AFTER the load: the SURVIVING replica
        # re-answers every completed request's ids.  Same platform as
        # the drill answers (replicas are CPU even when the parent
        # process sits on a chip — a parent-device reference would
        # compare fp32 across platforms and fail spuriously), and an
        # independent dispatch: cross-request row mixups or torn
        # batches during the failover cannot reproduce in a quiet
        # one-at-a-time re-query
        for i, rows in got.items():
            want = np.asarray(router.query(ids_seq[i],
                                           deadline_ms=60_000.0))
            if np.abs(rows - want).max() > 1e-5:
                wrong += 1
            else:
                ok += 1
        stats = router.stats()
    wall = time.perf_counter() - t_start
    denom = max(queries, 1)
    row = {"queries": queries, "ok": ok, "wrong": wrong,
           "timeout": timeout, "shed": shed, "other_errors": other,
           "failover": stats["n_failover"], "hedge": stats["n_hedge"],
           "replicas_alive": sum(1 for r in stats["replicas"]
                                 if r["alive"]),
           "availability": round(ok / denom, 4),
           "shed_rate": round(shed / denom, 4),
           "error_rate": round((timeout + other + wrong) / denom, 4),
           "wall_s": round(wall, 2)}
    if lat:
        row.update(_pcts(lat))
    return row


def run_shard_capacity(ds, model, cfg, art_root, queries=200,
                       batch=4, n_shards=2, mode="int8", trials=4,
                       seed=0):
    """The sharded-serving capacity proof (ISSUE 20 acceptance): the
    TOTAL propagation table exceeds one replica's enforced byte cap,
    yet the sharded fleet serves every query at availability 1.0 with
    answers bit-exact vs the full-table fleet.  Export ``--shards N``
    at ``mode``, front the slices with ``Router(sharded=True)`` under
    a ``table_budget_bytes`` cap BELOW the full table (a full-table
    replica would refuse to boot), drive load-gen with batches forced
    across the shard boundary, and pair an interleaved p50 A/B
    against a budget-free full-table router over the same artifact.

    The byte acceptance: per-replica bytes ≤ full/N + slack, where
    slack = halo rows + the pad row + the edge-balanced partition's
    imbalance over a perfect V/N split — the gather halo is the ONLY
    structural overhead a slice carries."""
    from roc_tpu.serve.export import build_predictor, export_predictor
    from roc_tpu.serve.quant import table_bytes
    from roc_tpu.serve.router import Router
    out_dir = os.path.join(art_root, "shard_capacity")
    t_start = time.perf_counter()
    pred = build_predictor(model, ds, cfg, backend="precomputed",
                           quant=mode)
    manifest = export_predictor(
        pred, out_dir,
        dataset_meta={"V": ds.graph.num_nodes,
                      "E": ds.graph.num_edges},
        shards=n_shards)
    sb = manifest["shards"]
    shard_bytes = int(sb["bytes_per_replica"])
    full_bytes = int(sb["bytes_full"])
    V, F = ds.graph.num_nodes, int(pred.cache.table.shape[1])
    # the cap: midway between one slice and the full table — a
    # full-table replica CANNOT boot under it, a slice fits
    budget = (shard_bytes + full_bytes) // 2
    slack = int(table_bytes(
        (int(sb["halo"]) + 1 + (int(sb["rows_padded"]) - V // n_shards),
         F), mode))
    bytes_ok = (shard_bytes <= budget < full_bytes
                and shard_bytes <= full_bytes // n_shards + slack)
    rng = np.random.RandomState(seed)
    ids_seq = [rng.randint(0, V, size=batch).astype(np.int32)
               for _ in range(queries)]
    # force a third of the batches across the first shard boundary —
    # a capacity row that never gathers proves nothing
    b = int(sb["plan"][0][1])
    for i in range(0, len(ids_seq), 3):
        ids_seq[i][:2] = (b - 1, b)
    want = [np.asarray(pred.query(ids)) for ids in ids_seq]
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("ROC_TPU_FAULT", None)
    wrong = 0
    p50s = {"full": [], "sharded": []}
    with Router(out_dir, n_replicas=n_shards, cpu=True, env=env,
                default_deadline_ms=60_000.0) as r_full, \
         Router(out_dir, n_replicas=n_shards, cpu=True, env=env,
                sharded=True, table_budget_bytes=budget,
                default_deadline_ms=60_000.0) as r_shard:
        # correctness + availability on the sharded arm first
        futs = [r_shard.submit(ids) for ids in ids_seq]
        for f, ref in zip(futs, want):
            if np.abs(np.asarray(f.result(timeout=120))
                      - ref).max() > 0.0:
                wrong += 1
        shard_stats = r_shard.stats()
        # paired interleaved p50 A/B (run_obs_ab precedent): both
        # routers warm, alternate arm order per trial
        arms = {"full": r_full, "sharded": r_shard}
        for trial in range(trials):
            order = (("full", "sharded") if trial % 2 == 0
                     else ("sharded", "full"))
            for name in order:
                lat = []
                for ids in ids_seq:
                    t0 = time.perf_counter()
                    arms[name].query(ids, deadline_ms=60_000.0)
                    lat.append((time.perf_counter() - t0) * 1e3)
                p50s[name].append(_pcts(lat)["p50_ms"])

    def _med(vs):
        vs = sorted(vs)
        n = len(vs)
        return vs[n // 2] if n % 2 else 0.5 * (vs[n // 2 - 1]
                                               + vs[n // 2])
    p50_full, p50_shard = _med(p50s["full"]), _med(p50s["sharded"])
    avail = shard_stats.get("availability")
    ok = bool(bytes_ok and wrong == 0 and avail == 1.0)
    return {"mode": mode, "n_shards": n_shards, "queries": queries,
            "ok": ok, "wrong": wrong, "availability": avail,
            "table_budget_bytes": budget,
            "serve_shard_table_bytes": shard_bytes,
            "full_table_bytes": full_bytes,
            "bytes_slack": slack, "bytes_ok": bytes_ok,
            "halo": int(sb["halo"]),
            "serve_gather_p50_ms": shard_stats.get("gather_p50_ms"),
            "p50_full_ms": round(p50_full, 4),
            "p50_sharded_ms": round(p50_shard, 4),
            "p50_full_all": [round(v, 4) for v in p50s["full"]],
            "p50_sharded_all": [round(v, 4)
                                for v in p50s["sharded"]],
            "delta_pct": round(100.0 * (p50_shard - p50_full)
                               / max(p50_full, 1e-9), 1),
            "wall_s": round(time.perf_counter() - t_start, 2)}


def run_shard_smoke(ds, model, cfg, art_root, queries=100,
                    batch=4, n_shards=2, mode="int8", seed=0):
    """The sharded-serving smoke (ISSUE 20 CI gate): export
    ``--shards 2``, cold-load ONE slice directly (the zero-new-
    compiles parity check inside ``load_predictor`` must pass), then
    front the slices with a 2-replica sharded Router under a byte cap
    below the full table and drive a load-gen pass whose batches
    straddle the shard boundary.  Every answer must match the
    export-process predictor bit-exactly.  Exit-enforced by
    scripts/test.sh preflight and round6_chain step 0: a fleet that
    cannot gather across its own shards never reaches a round."""
    from roc_tpu.serve.export import (build_predictor, export_predictor,
                                      load_predictor)
    from roc_tpu.serve.router import Router
    out_dir = os.path.join(art_root, "shard_smoke")
    t_start = time.perf_counter()
    pred = build_predictor(model, ds, cfg, backend="precomputed",
                           quant=mode)
    manifest = export_predictor(
        pred, out_dir,
        dataset_meta={"V": ds.graph.num_nodes,
                      "E": ds.graph.num_edges},
        shards=n_shards)
    sb = manifest["shards"]
    shard_bytes = int(sb["bytes_per_replica"])
    full_bytes = int(sb["bytes_full"])
    budget = (shard_bytes + full_bytes) // 2
    # cold slice load: program-key parity vs the manifest's shard warm
    # set is asserted inside load_predictor (raises on mismatch), and
    # a slice answers its OWNED ids bit-exactly with no gather path
    cold0 = load_predictor(out_dir, shard=0)
    lo0, hi0 = cold0.shard
    own_ids = np.arange(lo0, min(hi0, lo0 + batch), dtype=np.int32)
    cold_wrong = int(np.abs(np.asarray(cold0.query(own_ids))
                            - np.asarray(pred.query(own_ids))
                            ).max() > 0.0)
    rng = np.random.RandomState(seed)
    V = ds.graph.num_nodes
    ids_seq = [rng.randint(0, V, size=batch).astype(np.int32)
               for _ in range(queries)]
    b = int(sb["plan"][0][1])
    for i in range(0, len(ids_seq), 3):
        ids_seq[i][:2] = (b - 1, b)   # cross-shard ids, every 3rd
    want = [np.asarray(pred.query(ids)) for ids in ids_seq]
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("ROC_TPU_FAULT", None)   # a smoke is quiet by definition
    wrong = 0
    with Router(out_dir, n_replicas=n_shards, cpu=True, env=env,
                sharded=True, table_budget_bytes=budget,
                default_deadline_ms=60_000.0) as router:
        futs = [router.submit(ids) for ids in ids_seq]
        for f, ref in zip(futs, want):
            if np.abs(np.asarray(f.result(timeout=120))
                      - ref).max() > 0.0:
                wrong += 1
        stats = router.stats()
    avail = stats.get("availability")
    ok = bool(wrong == 0 and cold_wrong == 0 and avail == 1.0
              and shard_bytes <= budget < full_bytes)
    return {"mode": mode, "n_shards": n_shards, "queries": queries,
            "ok": ok, "wrong": wrong, "cold_slice_wrong": cold_wrong,
            "availability": avail,
            "table_budget_bytes": budget,
            "shard_table_bytes": shard_bytes,
            "full_table_bytes": full_bytes,
            "gather_p50_ms": stats.get("gather_p50_ms"),
            "wall_s": round(time.perf_counter() - t_start, 2)}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=20_000)
    ap.add_argument("--degree", type=int, default=8)
    ap.add_argument("--feat", type=int, default=128)
    ap.add_argument("--classes", type=int, default=16)
    ap.add_argument("--hops", type=int, default=2)
    ap.add_argument("--queries", type=int, default=200)
    ap.add_argument("--batch", type=int, default=4,
                    help="node ids per query (the per-user request "
                         "size; microbatching coalesces across them)")
    ap.add_argument("--rate", default="auto",
                    help="open-loop Poisson arrival rate in QPS "
                         "('auto' = half the measured closed-loop "
                         "throughput)")
    ap.add_argument("--backends", default="precomputed,full")
    ap.add_argument("--max-wait-ms", type=float, default=0.2)
    ap.add_argument("--drill", action="store_true",
                    help="also run the kill-a-replica router drill "
                         "(2 CPU replicas, replica 1 SIGKILLed "
                         "mid-load; availability/failover row)")
    ap.add_argument("--slo-smoke", action="store_true",
                    help="run ONLY the SLO smoke: export → cold-load "
                         "behind a 2-replica Router with declared "
                         "objectives → quiet load-gen → require "
                         "health green (exit 1 otherwise) — the CI "
                         "serving-tier gate")
    ap.add_argument("--quant-smoke", action="store_true",
                    help="run ONLY the quantized-serving smoke: "
                         "export int8 (drift gate must pass) → "
                         "cold-load → load-gen → served answers must "
                         "match the gated values bit-exactly (exit 1 "
                         "otherwise) — the PR-19 CI gate")
    ap.add_argument("--shard-smoke", action="store_true",
                    help="run ONLY the sharded-serving smoke: export "
                         "--shards 2 → cold-load one slice → sharded "
                         "Router under a byte cap below the full "
                         "table → load-gen with cross-shard ids, "
                         "bit-exact answers required (exit 1 "
                         "otherwise) — the PR-20 CI gate")
    ap.add_argument("--no-shard-ab", action="store_true",
                    help="skip the sharded-capacity row (2-shard "
                         "int8 export behind a byte-capped sharded "
                         "Router vs a full-table fleet; the "
                         "shard-bytes/gather acceptance)")
    ap.add_argument("--no-quant-ab", action="store_true",
                    help="skip the quant:int8 A/B row (precomputed "
                         "backend re-exported with --quantize int8; "
                         "the table-bytes/drift acceptance)")
    ap.add_argument("--no-obs-ab", action="store_true",
                    help="skip the instrumentation-off A/B row "
                         "(precomputed backend re-run with "
                         "instrument=False; the observability-"
                         "overhead acceptance)")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--out", default=None,
                    help="write the result JSON here (e.g. "
                         "benchmarks/micro_serve_cpu.json)")
    args = ap.parse_args(argv)
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    from roc_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache(min_compile_secs=0.0)
    dev = jax.devices()[0]
    ds, model, cfg = build_rig(args.nodes, args.degree, args.feat,
                               args.classes, args.hops)
    if args.quant_smoke:
        from roc_tpu.models.builder import Model
        with tempfile.TemporaryDirectory(prefix="roc_quant_") as art:
            row = run_quant_smoke(
                ds, Model.from_spec(model.to_spec()), cfg, art,
                queries=args.queries, batch=args.batch)
        drift = row.get("export_drift") or {}
        print(f"# quant smoke: {'GREEN' if row['ok'] else 'RED'} "
              f"({row['queries']} queries, mode {row['mode']}, "
              f"rel drift {drift.get('rel_dlogit')}, "
              f"shrink {row.get('table_shrink')}x, "
              f"{row.get('wrong', '?')} served mismatches)",
              file=sys.stderr)
        print(json.dumps(row))
        return 0 if row["ok"] else 1
    if args.shard_smoke:
        from roc_tpu.models.builder import Model
        with tempfile.TemporaryDirectory(prefix="roc_shard_") as art:
            row = run_shard_smoke(
                ds, Model.from_spec(model.to_spec()), cfg, art,
                queries=args.queries, batch=args.batch)
        print(f"# shard smoke: {'GREEN' if row['ok'] else 'RED'} "
              f"({row['queries']} queries over {row['n_shards']} "
              f"shards, {row['wrong']} wrong, availability "
              f"{row['availability']}, slice "
              f"{row['shard_table_bytes']} B ≤ cap "
              f"{row['table_budget_bytes']} B < full "
              f"{row['full_table_bytes']} B, gather p50 "
              f"{row['gather_p50_ms']} ms)", file=sys.stderr)
        print(json.dumps(row))
        return 0 if row["ok"] else 1
    if args.slo_smoke:
        from roc_tpu.models.builder import Model
        with tempfile.TemporaryDirectory(prefix="roc_slo_") as art:
            row = run_slo_smoke(
                ds, Model.from_spec(model.to_spec()), cfg, art,
                queries=args.queries, batch=args.batch)
        print(f"# slo smoke: {'GREEN' if row['ok'] else 'RED'} "
              f"({row['queries']} queries, availability "
              f"{row['availability']}, p99 {row['p99_ms']} ms)",
              file=sys.stderr)
        print(json.dumps(row))
        return 0 if row["ok"] else 1
    out = {"device": f"{dev.platform} {dev.device_kind}",
           "config": {"V": ds.graph.num_nodes,
                      "E": ds.graph.num_edges, "F": args.feat,
                      "C": args.classes, "k": args.hops,
                      "queries": args.queries, "batch": args.batch,
                      "max_wait_ms": args.max_wait_ms},
           "backends": {}}
    with tempfile.TemporaryDirectory(prefix="roc_serve_") as art:
        for backend in [b.strip()
                        for b in args.backends.split(",") if b.strip()]:
            from roc_tpu.models.builder import Model
            row = run_backend(
                backend, ds, Model.from_spec(model.to_spec()), cfg,
                args.queries, args.batch, args.rate, art)
            out["backends"][backend] = row
            print(f"# {backend}: closed p50 "
                  f"{row['closed']['p50_ms']} ms p99 "
                  f"{row['closed']['p99_ms']} ms "
                  f"{row['closed']['qps']} qps (queue p50 "
                  f"{row['closed'].get('queue_p50_ms')} / device p50 "
                  f"{row['closed'].get('device_p50_ms')} ms) | open "
                  f"p50 {row['open']['p50_ms']} ms p99 "
                  f"{row['open']['p99_ms']} ms", file=sys.stderr)
        if "precomputed" in out["backends"] and not args.no_quant_ab:
            # the quantized-serving A/B (PR 19): same backend, same
            # load, tables + params exported at int8 — the paired
            # quant:off/quant:int8 rows the table-bytes/drift
            # acceptance reads
            from roc_tpu.models.builder import Model
            row = run_backend(
                "precomputed", ds, Model.from_spec(model.to_spec()),
                cfg, args.queries, args.batch, args.rate, art,
                quant="int8")
            out["backends"]["precomputed_q8"] = row
            pre = out["backends"]["precomputed"]
            out["quant_ab"] = {
                "table_bytes_off": pre.get("table_bytes"),
                "table_bytes_int8": row.get("table_bytes"),
                "table_shrink": row.get("table_shrink"),
                "p50_off_ms": pre["closed"]["p50_ms"],
                "p50_int8_ms": row["closed"]["p50_ms"],
                "p99_off_ms": pre["closed"]["p99_ms"],
                "p99_int8_ms": row["closed"]["p99_ms"],
                "qps_off": pre["closed"]["qps"],
                "qps_int8": row["closed"]["qps"],
                "argmax_drift": row.get("argmax_drift"),
                "quant_drift": row.get("quant_drift")}
            # the headline p50 comparison comes from a PAIRED
            # interleaved A/B over the two cold-loaded artifacts —
            # the sequential rows above drift ±30% at sub-ms p50s
            from roc_tpu.serve.export import load_predictor
            p_off = load_predictor(os.path.join(art, "precomputed"))
            p_off.warm(name="serve_quant_ab_off")
            p_q8 = load_predictor(
                os.path.join(art, "precomputed_int8"))
            p_q8.warm(name="serve_quant_ab_int8")
            paired = run_quant_ab(p_off, p_q8, ds, args.queries,
                                  args.batch, args.max_wait_ms)
            out["quant_ab"]["paired"] = paired
            print(f"# quant A/B: table {pre.get('table_bytes')} B "
                  f"fp32 → {row.get('table_bytes')} B int8 "
                  f"({row.get('table_shrink')}x), paired p50 "
                  f"{paired['p50_off_ms']} → "
                  f"{paired['p50_int8_ms']} ms "
                  f"({paired['delta_pct']:+.1f}%), argmax drift "
                  f"{row.get('argmax_drift')}", file=sys.stderr)
        if "precomputed" in out["backends"] and not args.no_obs_ab:
            # the observability-overhead A/B: same backend, same
            # load, registry + trace stamping disarmed
            from roc_tpu.models.builder import Model
            row = run_backend(
                "precomputed", ds, Model.from_spec(model.to_spec()),
                cfg, args.queries, args.batch, args.rate,
                os.path.join(art, "noobs"), instrument=False)
            out["backends"]["precomputed_noobs"] = row
            # the headline overhead number comes from a PAIRED
            # interleaved A/B over one loaded predictor, not the two
            # independent rows above — at sub-ms p50s the sequential
            # rows disagree by ±30% on machine drift alone
            from roc_tpu.serve.export import load_predictor
            pred = load_predictor(os.path.join(art, "precomputed"))
            pred.warm(name="serve_obs_ab")
            ab = run_obs_ab(pred, ds, args.queries, args.batch,
                            args.max_wait_ms)
            out["obs_ab"] = ab
            out["obs_overhead_pct"] = ab["overhead_pct"]
            print(f"# obs overhead (paired A/B, median of "
                  f"{ab['trials']}): instrumented p50 "
                  f"{ab['p50_on_ms']} ms vs off {ab['p50_off_ms']} ms "
                  f"({ab['overhead_pct']:+.1f}%)", file=sys.stderr)
        if not args.no_shard_ab:
            # the sharded-capacity row (PR 20): total table above one
            # replica's byte cap, served sharded at availability 1.0
            # bit-exact, paired p50 vs the full-table fleet
            from roc_tpu.models.builder import Model
            row = run_shard_capacity(
                ds, Model.from_spec(model.to_spec()), cfg, art,
                queries=min(args.queries, 60), batch=args.batch)
            out["shard_capacity"] = row
            print(f"# shard capacity: {'OK' if row['ok'] else 'RED'} "
                  f"slice {row['serve_shard_table_bytes']} B ≤ cap "
                  f"{row['table_budget_bytes']} B < full "
                  f"{row['full_table_bytes']} B, {row['wrong']} "
                  f"wrong, availability {row['availability']}, "
                  f"paired p50 {row['p50_full_ms']} → "
                  f"{row['p50_sharded_ms']} ms "
                  f"({row['delta_pct']:+.1f}%), gather p50 "
                  f"{row['serve_gather_p50_ms']} ms",
                  file=sys.stderr)
        if args.drill:
            from roc_tpu.models.builder import Model
            row = run_router_drill(
                ds, Model.from_spec(model.to_spec()), cfg, art,
                batch=args.batch)
            out["router_drill"] = row
            print(f"# router drill: {row['ok']}/{row['queries']} ok, "
                  f"{row['wrong']} wrong, {row['timeout']} timeout, "
                  f"{row['failover']} failed over "
                  f"(availability {row['availability']})",
                  file=sys.stderr)
    pre = out["backends"].get("precomputed")
    full = out["backends"].get("full")
    if pre and full:
        out["speedup_p50"] = round(
            full["closed"]["p50_ms"] / max(pre["closed"]["p50_ms"],
                                           1e-9), 1)
        print(f"# precomputed vs full-graph p50 speedup: "
              f"{out['speedup_p50']}x", file=sys.stderr)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
        print(f"# wrote {args.out}", file=sys.stderr)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
