#!/usr/bin/env python
"""Convergence proof at scale: the production path must LEARN, not
just run (VERDICT r4 weak #5 / next #3).

The on-chip full-stage epoch timings use random-label synthetics
(meaningless accuracy by design); the accuracy gates live at toy scale
(512-node fixtures, 34-node karate).  This harness closes the gap: a
Reddit-shaped HOMOPHILOUS learnable synthetic (``core/graph.py
synthetic_dataset`` — class-informative features + mostly intra-class
edges, now vectorized to benchmark scale) trained for a few hundred
epochs on-chip through the PRODUCTION config (aggr_impl=auto ->
sectioned at this V, memory autopilot, mixed precision), with a gated
test accuracy and an explicit mixed-vs-fp32 parity check — bf16
sorted-scatter accumulation at 100k+ rows is exactly where numeric
drift would hide (VERDICT r4).

Convergence-as-test is the reference's own strategy
(``softmax_kernel.cu:141-152`` asserts on training behavior).

    python benchmarks/convergence_scale.py                # on-chip
    python benchmarks/convergence_scale.py --cpu \
        --nodes 3000 --avg-degree 10 --epochs 40          # rehearsal

Passing runs append a provenance record to
``benchmarks/measured_baselines.json`` — under
``convergence_at_scale`` for the production sectioned default, or
``convergence_at_scale_<impl>`` when another impl actually ran (e.g.
``--order label`` lets the auto probe resolve bdense at scale, which
records the MXU path's own numerics gate).  stdout: ONE JSON line.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

_BASELINES = os.path.join(
    os.environ.get("ROC_TPU_BENCH_ARTIFACTS", _HERE),
    "measured_baselines.json")


def build_parser():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=232_965)
    ap.add_argument("--avg-degree", type=int, default=60,
                    help="synthetic degree; 60 keeps the 300-epoch "
                         "run under ~10 min on v5e (full Reddit "
                         "degree 493 quintuples it without changing "
                         "what the gate proves)")
    ap.add_argument("--in-dim", type=int, default=602)
    ap.add_argument("--classes", type=int, default=41)
    ap.add_argument("--hidden", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=300)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--gate", type=float, default=0.85,
                    help="minimum test accuracy BOTH dtypes must hit")
    ap.add_argument("--parity", type=float, default=0.03,
                    help="max |acc_mixed - acc_fp32|")
    ap.add_argument("--homophily", type=float, default=0.8)
    ap.add_argument("--order", default="none",
                    choices=["none", "label"],
                    help="label: relabel vertices class-contiguous "
                         "(the oracle community order — intra-class "
                         "edges concentrate into [128,128] tiles, so "
                         "aggr_impl='auto''s structure probe selects "
                         "bdense at scale; metrics are relabeling-"
                         "invariant)")
    ap.add_argument("--impl", default="auto",
                    choices=["auto", "segment", "blocked", "scan",
                             "ell", "pallas", "sectioned", "bdense"],
                    help="aggregation impl (default auto: the "
                         "window + structure-probe resolution)")
    ap.add_argument("--cpu", action="store_true",
                    help="CPU rehearsal; result NOT recorded")
    return ap


def run_config(ds, args, dtype_name: str) -> dict:
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import (TrainConfig, Trainer,
                                       resolve_dtypes)
    dt, cdt = resolve_dtypes(dtype_name)
    cfg = TrainConfig(learning_rate=args.lr, weight_decay=1e-4,
                      decay_rate=0.97, decay_steps=100,
                      aggr_impl=args.impl, dtype=dt,
                      compute_dtype=cdt,
                      verbose=False, eval_every=1 << 30,
                      symmetric=True, memory="auto")
    model = build_gcn([args.in_dim, args.hidden, args.classes],
                      dropout_rate=0.5)
    t0 = time.time()
    tr = Trainer(model, ds, cfg)
    tr.train(epochs=2)
    tr.sync()
    compile_s = time.time() - t0
    t0 = time.time()
    tr.train(epochs=args.epochs - 2)
    tr.sync()
    train_s = time.time() - t0
    m = tr.evaluate()
    bd_tiles = (int(tr.gctx.bd_a.shape[0])
                if tr.gctx.bd_a is not None else 0)
    return {"dtype": dtype_name,
            "impl": tr.gctx.aggr_impl,
            **({"bdense_tiles": bd_tiles}
               if tr.gctx.aggr_impl == "bdense" else {}),
            "remat": bool(tr.config.remat),
            "epochs": args.epochs,
            "compile_s": round(compile_s, 1),
            "train_s": round(train_s, 1),
            "epoch_ms": round(train_s / max(args.epochs - 2, 1) * 1e3,
                              1),
            "train_acc": round(float(m["train_acc"]), 4),
            "test_acc": round(float(m["test_acc"]), 4)}


def main() -> int:
    args = build_parser().parse_args()
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache()
    dev = jax.devices()[0]

    t0 = time.time()
    ds = synthetic_dataset(args.nodes, args.avg_degree,
                           in_dim=args.in_dim,
                           num_classes=args.classes,
                           homophily=args.homophily, seed=7,
                           name="homophilous-scale")
    if args.order == "label":
        # class-contiguous relabel: the oracle community order (the
        # generator's intra-class edges land in per-class diagonal
        # tile blocks); accuracy is invariant, the aggregation layout
        # is not — this is what lets 'auto' probe its way to bdense
        from roc_tpu.core.reorder import apply_vertex_order
        order = np.argsort(ds.labels, kind="stable").astype(np.int32)
        ds, _ = apply_vertex_order(ds, order, order_name="label")
    gen_s = time.time() - t0
    print(f"# {dev.platform} {dev.device_kind}: V={ds.graph.num_nodes:,}"
          f" E={ds.graph.num_edges:,} gen {gen_s:.0f}s "
          f"order={args.order}",
          file=sys.stderr)

    results = {}
    for dtype_name in ("float32", "mixed"):
        t0 = time.time()
        results[dtype_name] = run_config(ds, args, dtype_name)
        r = results[dtype_name]
        print(f"# {dtype_name}: test_acc={r['test_acc']:.4f} "
              f"train_acc={r['train_acc']:.4f} impl={r['impl']} "
              f"epoch={r['epoch_ms']}ms ({time.time()-t0:.0f}s)",
              file=sys.stderr)

    acc_f, acc_m = (results["float32"]["test_acc"],
                    results["mixed"]["test_acc"])
    gap = abs(acc_f - acc_m)
    ok = acc_f >= args.gate and acc_m >= args.gate \
        and gap <= args.parity
    # key by the impl that ACTUALLY ran: the plain key is the
    # production sectioned default's baseline; any other impl gets
    # its own suffix (a bdense claim additionally requires dense
    # tiles to have executed — a residual-only fallback must not
    # record as MXU-path numerics)
    impl_ran = results["mixed"]["impl"]
    metric = "convergence_at_scale"
    if impl_ran == "bdense":
        metric += ("_bdense"
                   if min(r.get("bdense_tiles", 0)
                          for r in results.values()) > 0
                   else "_bdense_no_tiles")
    elif impl_ran != "sectioned":
        metric += f"_{impl_ran}"
    line = {"metric": metric,
            "ok": bool(ok), "gate": args.gate,
            "V": ds.graph.num_nodes, "E": int(ds.graph.num_edges),
            "order": args.order,
            "parity_gap": round(gap, 4),
            "platform": dev.platform, "device_kind": dev.device_kind,
            "float32": results["float32"], "mixed": results["mixed"]}
    if ok and not args.cpu and dev.platform in ("tpu", "axon"):
        try:
            with open(_BASELINES) as f:
                db = json.load(f)
        except (OSError, ValueError):
            db = {}
        rec = dict(line)
        rec["recorded"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
        rec["provenance"] = ("benchmarks/convergence_scale.py "
                             f"--order {args.order} --impl {args.impl}")
        db.setdefault(metric, rec)
        tmp = _BASELINES + ".tmp"
        with open(tmp, "w") as f:
            json.dump(db, f, indent=1, sort_keys=True)
        os.replace(tmp, _BASELINES)
        print(f"# recorded -> {_BASELINES}", file=sys.stderr)
    print(json.dumps(line))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
