#!/usr/bin/env python
"""Micro-benchmark: pipelined execution — h2d prefetch + ring overlap.

Three probes, matching the two latency-hiding paths this repo grew out
of ROC's ZC→FB staging loop and ring exchange:

1. **head race** — ``StreamedHead.forward``/``wgrad`` with the staging
   pool at each ``--prefetch`` depth: wall ms, ``h2d_wait`` p50 (the
   un-hidden per-block stall) and ``overlap_frac`` (fraction of staging
   latency hidden under compute; 0 by construction for the synchronous
   depth-0 reference).
2. **streamed-tier epochs** — a short ``features='host'`` training run
   per depth; the checked-in epoch records are the acceptance artifact:
   the prefetched run must report a reduced ``h2d_wait_p50_ms`` and a
   positive ``overlap_frac`` vs. the synchronous run.
3. **ring overlap** — ``ring_aggregate`` with the double-buffered hop
   schedule vs. the sequential compute-then-permute reference on a
   P-device mesh, plus a permute-only isolation loop; hop_compute is
   the derived remainder (sequential − permute-only — the local
   aggregation cannot run standalone without the rotation feeding
   it).  Emitted as ``pipeline`` events so ``python -m
   roc_tpu.report`` can show where the hop time goes.

Usage: python benchmarks/micro_stream.py [--cpu] [--out out.json]
The CPU rehearsal artifact lives at benchmarks/micro_stream_cpu.json.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def bench(fn, iters=10):
    """Median wall ms with the fetch-based barrier (micro_agg.py)."""
    import jax.numpy as jnp
    out = fn()
    float(jnp.sum(out))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn()
        float(jnp.sum(out))
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times))


def _pool_row(ms, stats):
    # wait/stage medians and overlap_frac are computed by
    # StagingPool.take_stats itself — one formula for every consumer
    return {"ms": round(ms, 2),
            "h2d_wait_p50_ms": stats["wait_p50_ms"],
            "h2d_stage_p50_ms": stats["stage_p50_ms"],
            "overlap_frac": stats["overlap_frac"],
            "max_live_blocks": int(stats["max_live"])}


def head_race(args):
    """StreamedHead fwd/wgrad per prefetch depth."""
    import jax
    import jax.numpy as jnp
    from roc_tpu.core.streaming import StreamedHead
    V, F, H, bs = args.nodes, args.dim, args.hidden, args.block_rows
    rng = np.random.RandomState(0)
    X = rng.rand(V, F).astype(np.float32)
    W = jnp.asarray(rng.rand(F, H).astype(np.float32))
    dY = jnp.asarray(rng.rand(V, H).astype(np.float32))
    key = jax.random.PRNGKey(0)
    rows = {}
    for depth in args.depths:
        head = StreamedHead(0.3, block_rows=bs, prefetch=depth)
        fwd_ms = bench(lambda: head.forward(W, X, key, True),
                       args.iters)
        # stats reset on take: pair each phase's wall time with the
        # staging series recorded DURING that phase
        row = _pool_row(fwd_ms, head.pool.take_stats())
        wg_ms = bench(lambda: head.wgrad(X, dY, key, True), args.iters)
        wg_stats = head.pool.take_stats()
        row.update(wgrad_ms=round(wg_ms, 2),
                   wgrad_overlap_frac=wg_stats["overlap_frac"],
                   wgrad_h2d_wait_p50_ms=wg_stats["wait_p50_ms"],
                   prefetch=depth)
        rows[f"prefetch:{depth}"] = row
    return rows


def epoch_records(args):
    """features='host' training per depth — the epoch records carry
    overlap_frac / h2d_wait_p50_ms (run_epoch_loop pipeline fields).
    The summary compares record medians: the prefetched tier must show
    a reduced h2d_wait p50 and a positive overlap_frac vs. the
    synchronous (depth 0) reference."""
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import TrainConfig, Trainer
    V = min(args.nodes, 65_536)
    F, H = args.epoch_dim, args.hidden
    ds = synthetic_dataset(V, 6, in_dim=F, num_classes=8, seed=1)
    records, summary = {}, {}
    for depth in args.depths:
        model = build_gcn([F, H, 8], dropout_rate=0.3)
        cfg = TrainConfig(learning_rate=0.01, features="host",
                          prefetch=depth, epochs=args.epochs,
                          eval_every=2, verbose=False, symmetric=True)
        tr = Trainer(model, ds, cfg)
        hist = tr.train()
        keep = ("epoch", "epoch_ms", "overlap_frac",
                "h2d_wait_p50_ms", "h2d_stage_p50_ms",
                "prefetch_depth")
        records[f"prefetch:{depth}"] = [
            {k: m[k] for k in keep if k in m} for m in hist]
        waits = [m["h2d_wait_p50_ms"] for m in hist
                 if "h2d_wait_p50_ms" in m]
        fracs = [m.get("overlap_frac", 0.0) for m in hist
                 if "h2d_wait_p50_ms" in m]
        summary[f"prefetch:{depth}"] = {
            "h2d_wait_p50_ms_median": round(
                float(np.median(waits)), 3) if waits else None,
            "overlap_frac_max": round(float(max(fracs)), 4)
            if fracs else None}
    out = {"records": records, "summary": summary}
    s0 = summary.get("prefetch:0")
    pre = [summary[f"prefetch:{d}"] for d in args.depths if d > 0
           and f"prefetch:{d}" in summary]
    if s0 and pre and s0["h2d_wait_p50_ms_median"] is not None:
        # any prefetched depth counts: per-record overlap_frac on a
        # contended CPU host is noisy (the burst folds eval passes
        # in), but the un-hidden wait and at least one overlapped
        # depth must beat the synchronous reference
        out["win"] = {
            "h2d_wait_reduced": bool(min(
                s["h2d_wait_p50_ms_median"] for s in pre)
                < s0["h2d_wait_p50_ms_median"]),
            "overlap_present": bool(max(
                (s["overlap_frac_max"] or 0) for s in pre) > 0)}
    return out


def ring_overlap(args):
    """ring_aggregate overlapped vs sequential + hop isolation."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from roc_tpu.core.graph import synthetic_dataset
    from roc_tpu.core.partition import partition_graph
    from roc_tpu.obs.events import emit
    from roc_tpu.parallel import ring as R
    from roc_tpu.parallel.distributed import (_shard_map, make_mesh,
                                              pad_nodes)
    parts = min(args.parts, len(jax.devices()))
    if parts < 2:
        return {"skipped": f"{len(jax.devices())} device(s)"}
    V = min(args.nodes, 32_768)
    ds = synthetic_dataset(V, 8, in_dim=args.dim, num_classes=4,
                           seed=2)
    pg = partition_graph(ds.graph, parts, node_multiple=8)
    rt = R.build_ring_tables(pg)
    mesh = make_mesh(parts)
    x = jnp.asarray(pad_nodes(
        np.random.RandomState(3).rand(V, args.dim).astype(np.float32),
        pg))
    src, dst = jnp.asarray(rt.src), jnp.asarray(rt.dst)
    spec = (P("parts"),) * 3
    rows = {}
    for name, overlap in (("sequential", False), ("overlapped", True)):
        body = lambda xb, sb, db, o=overlap: R.ring_aggregate(
            xb[0], sb[0], db[0], overlap=o)[None]
        f = jax.jit(_shard_map(body, mesh, spec, P("parts")))
        rows[name] = {"ms": round(bench(lambda: f(x, src, dst),
                                        args.iters), 3)}

    # hop isolation: P hops of ONLY the rotation — what a sequential
    # ring pays in pure comm; hop_compute is the derived remainder
    # (the local scatter-accumulate has no standalone form: it needs
    # the rotation feeding its buffer)
    def permute_only(xb, sb, db):
        xl = xb[0]
        perm = [(i, (i + 1) % parts) for i in range(parts)]
        step = lambda k, b: lax.ppermute(b, "parts", perm)
        return lax.fori_loop(0, parts, step, xl)[None]

    fp = jax.jit(_shard_map(permute_only, mesh, spec, P("parts")))
    rows["hop_permute"] = {"ms": round(bench(
        lambda: fp(x, src, dst), args.iters), 3)}
    rows["hop_compute_ms_est"] = round(
        max(0.0, rows["sequential"]["ms"]
            - rows["hop_permute"]["ms"]), 3)
    emit("pipeline", "micro_stream ring probe", console=False,
         hop_permute_ms=rows["hop_permute"]["ms"],
         hop_compute_ms=rows["hop_compute_ms_est"],
         sequential_ms=rows["sequential"]["ms"],
         overlapped_ms=rows["overlapped"]["ms"], parts=parts)
    return {"parts": parts, "V": V, **rows}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=262_144)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--block-rows", type=int, default=32_768)
    ap.add_argument("--epoch-dim", type=int, default=256,
                    help="input width of the epoch-record probe "
                         "(wider features = heavier per-block staging "
                         "= a cleaner overlap signal)")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--parts", type=int, default=4)
    ap.add_argument("--depths", type=str, default="0,1,2",
                    help="comma list of staging-pool prefetch depths")
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--out", type=str, default=None,
                    help="write the result JSON here too")
    args = ap.parse_args()
    args.depths = [int(d) for d in args.depths.split(",")]

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    dev = jax.devices()[0]
    print(f"# device={dev.platform} {dev.device_kind} "
          f"V={args.nodes} F={args.dim} H={args.hidden} "
          f"block_rows={args.block_rows}", file=sys.stderr)

    result = {
        "device": f"{dev.platform} {dev.device_kind}",
        "config": {"V": args.nodes, "F": args.dim, "H": args.hidden,
                   "block_rows": args.block_rows, "iters": args.iters,
                   "epochs": args.epochs},
        "head": head_race(args),
        "epochs": epoch_records(args),
        "ring": ring_overlap(args),
    }
    line = json.dumps(result, indent=1)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
