#!/usr/bin/env python
"""Compile-size probe for the attention paths at ogbn-products scale.

BASELINE.md config 7 (GAT, V=2.45M, E=126M) could not land on one chip
in r3: the per-width bucket path (ops/attention.py gat_aggregate_ell)
Python-unrolls one checkpointed scan per large width bucket, autodiff
doubles each, and the resulting HLO pushed remote compile past 40 min.
This probe LOWERS (traces, no backend compile — runs anywhere) the
differentiated aggregation for both layouts at the real shapes and
reports StableHLO module size — the controlled evidence that the
uniform flat8 layout (gat_aggregate_flat8) removes the blowup.

Usage: python benchmarks/compile_probe.py [--nodes N] [--edges E]
       [--dim F] [--heads K]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def bucket_shapes(deg: np.ndarray, min_width: int = 8):
    """(R, W) per bucket from the degree sequence — the shapes
    ell_from_graph would build, without materializing any tables."""
    from roc_tpu.core.ell import row_widths
    w = row_widths(deg, min_width)
    out = []
    for wv, c in zip(*np.unique(w[w > 0], return_counts=True)):
        out.append((int(c), int(wv)))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=2_449_029)
    ap.add_argument("--edges", type=int, default=126_000_000)
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--heads", type=int, default=1)
    ap.add_argument("--seg-rows", type=int, default=8192)
    args = ap.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")  # lowering only
    import jax.numpy as jnp
    from roc_tpu.ops.attention import (gat_aggregate_ell,
                                       gat_aggregate_flat8)

    V, F, K = args.nodes, args.dim, args.heads
    rng = np.random.RandomState(0)
    from roc_tpu.core.graph import _lognormal_degree_sequence
    deg = _lognormal_degree_sequence(V, args.edges, rng)

    S = jax.ShapeDtypeStruct
    full = S((V + 1, F), jnp.float32)
    s_full = S((V + 1, K), jnp.float32)
    d_local = S((V + 1, K), jnp.float32)

    def lower(tag, fn, *extra):
        t0 = time.time()
        lowered = jax.jit(jax.grad(
            lambda f, s, d, *a: jnp.sum(fn(f, s, d, *a) ** 2),
            argnums=(0, 1, 2))).lower(full, s_full, d_local, *extra)
        txt = lowered.as_text()
        print(f"{tag:10s} HLO {len(txt)/1e6:8.2f} MB "
              f"{txt.count(chr(10)):9d} lines   "
              f"(lowered in {time.time()-t0:.1f}s)")
        return len(txt)

    # bucket path: shapes exactly as ell_from_graph would plan them
    shapes = bucket_shapes(deg)
    print(f"# V={V} E={args.edges} F={F} K={K}; "
          f"{len(shapes)} width buckets "
          f"(max width {max(w for _, w in shapes)})")
    idx = tuple(S((r, w), jnp.int32) for r, w in shapes)
    rid = tuple(S((r,), jnp.int32) for r, _ in shapes)
    pos = S((V,), jnp.int32)
    b = lower("bucket", lambda f, s, d, i, ri, p:
              gat_aggregate_ell(f, s, d, i, ri, p, V), idx, rid, pos)

    # flat8 path: one uniform [chunks, seg, 8] table
    n_sub = int((-(-deg // 8)).sum())
    chunks = -(-n_sub // args.seg_rows)
    f8i = S((chunks, args.seg_rows, 8), jnp.int32)
    f8d = S((chunks, args.seg_rows), jnp.int32)
    f = lower("flat8", lambda fu, s, d, i8, d8:
              gat_aggregate_flat8(fu, s, d, i8, d8, V), f8i, f8d)
    print(f"# flat8 table: {chunks} chunks x {args.seg_rows} x 8 "
          f"({n_sub/1e6:.1f}M sub-rows); HLO ratio bucket/flat8 = "
          f"{b / f:.1f}x")


if __name__ == "__main__":
    main()
