"""Shared benchmark-substrate spec parser (micro_agg.py,
blockdense_occupancy.py): ONE grammar for the synthetic graphs the
aggregation races run on.

    random               uniform sources (the headline synthetic)
    planted[:ROWS]       ROWS-row communities, SHUFFLED vertex ids
    plantedo[:ROWS]      same, ORACLE order (upper bound for any
                         reordering pass)
    skew[:A]             hub sources, u**(1+A) mapping
"""

GRAPH_SPEC_HELP = ("random | planted[:COMMUNITY_ROWS] (community "
                   "structure with shuffled ids) | "
                   "plantedo[:COMMUNITY_ROWS] (same, ORACLE vertex "
                   "order — upper bound for any reordering pass) | "
                   "skew[:A] (hub sources, u**(1+A) mapping)")


def graph_from_spec(spec: str, V: int, E: int):
    from roc_tpu.core.graph import planted_community_csr, random_csr
    parts = spec.split(":")
    if parts[0] == "random":
        return random_csr(V, E, seed=0)
    if parts[0] in ("planted", "plantedo"):
        rows = int(parts[1]) if len(parts) > 1 else 65_536
        return planted_community_csr(V, E, community_rows=rows, seed=0,
                                     shuffle=(parts[0] == "planted"))
    if parts[0] == "skew":
        a = float(parts[1]) if len(parts) > 1 else 3.0
        # one community spanning the whole graph + skewed member pick
        # = globally hub-skewed sources
        return planted_community_csr(V, E, community_rows=V,
                                     intra_frac=1.0, seed=0,
                                     shuffle=False, src_skew=a)
    raise SystemExit(f"unknown --graph {spec!r}")


def reorder_graph(g, name: str):
    """Apply a registered ordering pass (or 'none'); returns
    (graph, seconds)."""
    if name == "none":
        return g, 0.0
    import time

    from roc_tpu.core.reorder import ORDERINGS, apply_graph_order
    if name not in ORDERINGS:
        raise SystemExit(f"unknown --reorder {name!r}")
    t0 = time.time()
    g = apply_graph_order(g, ORDERINGS[name](g))
    return g, time.time() - t0
