"""Shared benchmark-substrate spec parser (micro_agg.py,
blockdense_occupancy.py): ONE grammar for the synthetic graphs the
aggregation races run on.

    random               uniform sources (the headline synthetic)
    planted[:ROWS]       ROWS-row communities, SHUFFLED vertex ids
    plantedo[:ROWS]      same, ORACLE order (upper bound for any
                         reordering pass)
    skew[:A]             hub sources, u**(1+A) mapping
    zipf[:A]             Zipf in-degrees rank^-A (hub DESTINATIONS —
                         the edge-balanced-partitioning stress case)
"""

GRAPH_SPEC_HELP = ("random | planted[:COMMUNITY_ROWS] (community "
                   "structure with shuffled ids) | "
                   "plantedo[:COMMUNITY_ROWS] (same, ORACLE vertex "
                   "order — upper bound for any reordering pass) | "
                   "skew[:A] (hub sources, u**(1+A) mapping) | "
                   "zipf[:A] (Zipf rank^-A in-degrees, hub "
                   "destinations)")


def graph_from_spec(spec: str, V: int, E: int):
    from roc_tpu.core.graph import planted_community_csr, random_csr
    parts = spec.split(":")
    if parts[0] == "random":
        return random_csr(V, E, seed=0)
    if parts[0] in ("planted", "plantedo"):
        rows = int(parts[1]) if len(parts) > 1 else 65_536
        return planted_community_csr(V, E, community_rows=rows, seed=0,
                                     shuffle=(parts[0] == "planted"))
    if parts[0] == "zipf":
        from roc_tpu.core.graph import zipf_csr
        a = float(parts[1]) if len(parts) > 1 else 1.0
        return zipf_csr(V, E, a=a, seed=0)
    if parts[0] == "skew":
        a = float(parts[1]) if len(parts) > 1 else 3.0
        # one community spanning the whole graph + skewed member pick
        # = globally hub-skewed sources
        return planted_community_csr(V, E, community_rows=V,
                                     intra_frac=1.0, seed=0,
                                     shuffle=False, src_skew=a)
    raise SystemExit(f"unknown --graph {spec!r}")


def reorder_graph(g, name: str, cache_key: str = None):
    """Apply a registered ordering pass (or 'none'); returns
    (graph, seconds).

    ``cache_key`` (e.g. ``f"{spec}_{V}_{E}"`` from the generating
    flags) caches the PERMUTATION on disk under
    ``benchmarks/.reorder_cache/``: the substrate generators are
    seed-deterministic, so the same spec always yields the same graph
    and the one-time 2-5 min lpa pass at Reddit scale need not be
    repaid by every benchmark invocation (it repeatedly pushed
    chip-side runs into their timeouts).  The cached file stores the
    permutation, not the graph — O(V) bytes; a loaded file is
    verified to BE a permutation of [0, V) (a corrupt one is
    recomputed, since apply_graph_order itself only checks shape and
    would relabel silently wrong)."""
    if name == "none":
        return g, 0.0
    import hashlib
    import os
    import sys
    import time

    from roc_tpu.core import reorder as _reorder_mod
    from roc_tpu.core.reorder import ORDERINGS, apply_graph_order
    if name not in ORDERINGS:
        raise SystemExit(f"unknown --reorder {name!r}")
    cache_path = None
    if cache_key is not None:
        # the ordering module's source hash versions the key: editing
        # the lpa/bfs pass auto-invalidates cached permutations (these
        # benchmarks MEASURE ordering quality — serving a stale perm
        # would silently report the old algorithm's numbers)
        with open(_reorder_mod.__file__, "rb") as f:
            algo_ver = hashlib.sha1(f.read()).hexdigest()[:8]
        cache_dir = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), ".reorder_cache")
        cache_path = os.path.join(
            cache_dir,
            f"{cache_key}_{name}_{algo_ver}.npy".replace(":", ""))
        if os.path.exists(cache_path):
            import numpy as np
            t0 = time.time()
            try:
                perm = np.load(cache_path)
            except (ValueError, OSError, EOFError):
                perm = np.empty(0)   # corrupt file -> recompute
            if (perm.shape == (g.num_nodes,)
                    and np.array_equal(np.sort(perm),
                                       np.arange(g.num_nodes))):
                print(f"# cached {name} perm: {cache_path}",
                      file=sys.stderr)
                return (apply_graph_order(g, perm),
                        time.time() - t0)
    t0 = time.time()
    perm = ORDERINGS[name](g)
    g = apply_graph_order(g, perm)
    took = time.time() - t0
    if cache_path is not None:
        import numpy as np
        os.makedirs(os.path.dirname(cache_path), exist_ok=True)
        # pid-unique tmp name: concurrent benchmark invocations must
        # not interleave writes into one file (np.save appends .npy
        # unless the name already ends with it)
        tmp = f"{cache_path}.{os.getpid()}.tmp.npy"
        np.save(tmp, perm)
        os.replace(tmp, cache_path)
    return g, took
