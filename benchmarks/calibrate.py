#!/usr/bin/env python
"""Sectioned-window calibration harness: one command per generation.

``resolve_auto_impl`` picks between the whole-table ELL gather and the
sectioned carry-scan from a generation-keyed window
(``core/ell.py SECTIONED_BOUNDS_BY_KIND``).  That window is a MEASURED
property of a chip generation — on v5e the crossover was found by hand
(BASELINE.md "ell vs sectioned across graph size").  This harness
automates the sweep so an uncalibrated generation becomes a calibrated
one with one command (VERDICT r4 weak #4):

    python benchmarks/calibrate.py            # on the chip to calibrate
    python benchmarks/calibrate.py --cpu      # rehearsal, not recorded

Protocol: at each V point (default 233k / 500k / 1M — bracketing the
v5e crossover) build a random CSR at CONSTANT average degree
(``--degree``, default 60) so every point measures the same density
regime — the ell-vs-sectioned winner depends on density, and a sweep
that thins out as V grows would calibrate a window for a workload mix
nobody runs.  Time one F=256 aggregation per impl (median of
``--iters``) and place the upper out_rows bound at the geometric mean
between the largest V where ``sectioned`` wins and the smallest V
where ``ell`` wins back.  The lower bound stays
``SECTION_ROWS_DEFAULT`` (below one section's rows the layouts
coincide and the sectioned overhead can only lose).  Degree is stored
in the provenance row; calibrate at your own workload's density with
explicit ``V:E`` points if it differs a lot.

The measured row is merged into ``benchmarks/calibration.json``
(override: ``ROC_TPU_CALIBRATION``), which ``sectioned_bounds`` reads
over the builtin table — no code edit, no restart.  Raw point timings
are stored alongside the row as provenance.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))


def build_parser():
    ap = argparse.ArgumentParser()
    ap.add_argument("--points", type=str,
                    default="233000,500000,1000000",
                    help="comma list of sweep points: bare V (edges = "
                         "V * --degree) or explicit V:E")
    ap.add_argument("--degree", type=int, default=60,
                    help="average degree for bare-V points (constant "
                         "density across the sweep)")
    ap.add_argument("--feat", type=int, default=256)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--dtype", type=str, default="bfloat16",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--cpu", action="store_true",
                    help="CPU backend rehearsal; result is printed but "
                         "NOT recorded (the window is a chip property)")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the sweep plan and exit (no backend)")
    return ap


def measure_point(V: int, E: int, F: int, iters: int, dtype_str: str
                  ) -> dict:
    import jax
    import jax.numpy as jnp
    from roc_tpu.core.ell import ell_from_graph, sectioned_from_graph
    from roc_tpu.core.graph import random_csr
    from roc_tpu.ops.aggregate import aggregate_ell, aggregate_ell_sect
    from roc_tpu.utils.profiling import sync

    g = random_csr(V, E, seed=0)
    feats_np = np.random.RandomState(0).rand(V + 1, F).astype(np.float32)
    feats_np[-1] = 0
    feats = jnp.asarray(feats_np, dtype=jnp.dtype(dtype_str))

    def bench(fn):
        sync(fn())
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            sync(fn())
            times.append((time.perf_counter() - t0) * 1e3)
        return float(np.median(times))

    table = ell_from_graph(g.row_ptr, g.col_idx, V)
    idx = tuple(jnp.asarray(a[0]) for a in table.idx)
    pos = jnp.asarray(table.row_pos[0])
    f_ell = jax.jit(lambda x: aggregate_ell(x, idx, pos, V))
    ell_ms = bench(lambda: f_ell(feats))

    sect = sectioned_from_graph(g.row_ptr, g.col_idx, V)
    sidx, sdst, meta = sect.as_jax()
    f_sect = jax.jit(lambda x, i, d: aggregate_ell_sect(x, i, d, meta, V))
    sect_ms = bench(lambda: f_sect(feats, sidx, sdst))
    return {"V": V, "E": E, "ell_ms": round(ell_ms, 1),
            "sectioned_ms": round(sect_ms, 1),
            "winner": "sectioned" if sect_ms < ell_ms else "ell"}


def bounds_from_points(points: list, lo: int) -> tuple:
    """Upper bound from the win->loss crossover in an ascending-V
    sweep: geometric mean of the bracketing Vs; all-win extrapolates
    2x past the sweep, all-loss collapses the window to ``lo``."""
    wins = [p["V"] for p in points if p["winner"] == "sectioned"]
    losses = [p["V"] for p in points if p["winner"] == "ell"
              and p["V"] > lo]
    if not wins:
        return lo, lo  # empty window: auto always picks ell
    hi_wins = max(wins)
    later_losses = [v for v in losses if v > hi_wins]
    if not later_losses:
        return lo, int(hi_wins * 2)
    return lo, int(np.sqrt(hi_wins * min(later_losses)))


def main() -> int:
    args = build_parser().parse_args()
    points = []
    for spec in args.points.split(","):
        if ":" in spec:
            v, e = spec.split(":")
            points.append((int(v), int(e)))
        else:
            v = int(spec)
            points.append((v, v * args.degree))
    points.sort()
    if args.dry_run:
        print(json.dumps({"plan": points}))
        return 0

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    from roc_tpu.core.ell import SECTION_ROWS_DEFAULT, calibration_path
    from roc_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache()
    dev = jax.devices()[0]
    kind = dev.device_kind
    print(f"# calibrating {kind!r} ({dev.platform}), F={args.feat} "
          f"{args.dtype}, {len(points)} points", file=sys.stderr)

    measured = []
    for V, E in points:
        t0 = time.time()
        rec = measure_point(V, E, args.feat, args.iters, args.dtype)
        measured.append(rec)
        print(f"# V={V:>9,} E={E:>12,}: ell {rec['ell_ms']:>8.1f} ms  "
              f"sectioned {rec['sectioned_ms']:>8.1f} ms  -> "
              f"{rec['winner']}  ({time.time()-t0:.0f}s)",
              file=sys.stderr)

    lo, hi = bounds_from_points(measured, SECTION_ROWS_DEFAULT)
    row = {"lo": lo, "hi": hi,
           "recorded": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
           "feat": args.feat, "dtype": args.dtype,
           "degree": args.degree,
           "points": measured,
           "provenance": "benchmarks/calibrate.py"}
    out = {"device_kind": kind, "lo": lo, "hi": hi,
           "recorded": args.cpu is False}
    if args.cpu:
        print(f"# --cpu rehearsal: row NOT recorded", file=sys.stderr)
    else:
        path = calibration_path()
        try:
            with open(path) as f:
                db = json.load(f)
        except (OSError, ValueError):
            db = {}
        db[kind] = row
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(db, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        print(f"# recorded {kind!r}: (lo={lo}, hi={hi}) -> {path}",
              file=sys.stderr)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
