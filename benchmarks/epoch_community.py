#!/usr/bin/env python
"""Full-epoch race on the community substrate: does the block-dense
aggregation win survive end-to-end?

The micro race (micro_agg.py --impls sectioned,bdense) measures ONE
aggregation; an epoch is 2 forward + 2 backward aggregations plus the
dense stack, so this script runs the headline GCN workload
(602-256-41, dropout 0.5, Adam — example_run.sh:1 semantics) through
complete training epochs per impl on the SAME reordered community
graph.  The aggregation is ~98% of the epoch (BASELINE.md), so the
micro win should transfer near-1:1; this record is the proof.

    python benchmarks/epoch_community.py            # planted:16384+lpa

Records to measured_baselines.json:
full_graph_gcn_epoch_time_community when run on the chip.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

HERE = os.path.dirname(os.path.abspath(__file__))
_BASELINES = os.path.join(HERE, "measured_baselines.json")
METRIC = "full_graph_gcn_epoch_time_community"


def main() -> int:
    from _substrates import GRAPH_SPEC_HELP
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=232_965)
    ap.add_argument("--edges", type=int, default=114_848_857)
    ap.add_argument("--layers", default="602-256-41")
    ap.add_argument("--dtype", default="mixed",
                    choices=["float32", "bfloat16", "mixed"])
    ap.add_argument("--impls", default="sectioned,bdense")
    ap.add_argument("--epochs", type=int, default=10,
                    help="timed epochs per impl (median recorded)")
    ap.add_argument("--graph", default="planted:16384",
                    help=GRAPH_SPEC_HELP)
    ap.add_argument("--reorder", default="lpa",
                    choices=["none", "bfs", "lpa"])
    ap.add_argument("--min-fill", type=int, default=64)
    ap.add_argument("--a-budget", type=int, default=2 << 30,
                    help="bdense A-table byte cap (0 = uncapped)")
    ap.add_argument("--bdense-group", type=int, default=1,
                    help="dense blocks reduced per output-tile update "
                         "(pad_plan_groups; cuts output RMW traffic)")
    ap.add_argument("--cpu", action="store_true",
                    help="CPU rehearsal; result NOT recorded")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    from _substrates import graph_from_spec, reorder_graph
    from roc_tpu.core.graph import Dataset
    from roc_tpu.models.gcn import build_gcn
    from roc_tpu.train.trainer import (TrainConfig, Trainer,
                                       resolve_dtypes)
    from roc_tpu.utils.compile_cache import enable_compile_cache
    enable_compile_cache()
    dev = jax.devices()[0]
    layers = [int(x) for x in args.layers.split("-")]

    t0 = time.time()
    graph = graph_from_spec(args.graph, args.nodes, args.edges)
    gen_s = time.time() - t0
    graph, reorder_s = reorder_graph(
        graph, args.reorder,
        cache_key=f"{args.graph}_{args.nodes}_{args.edges}")
    print(f"# {dev.platform} {dev.device_kind}: "
          f"V={graph.num_nodes:,} E={graph.num_edges:,} "
          f"gen {gen_s:.0f}s, {args.reorder} reorder {reorder_s:.0f}s",
          file=sys.stderr)

    # random labels/split like bench.py's headline stage: epoch TIME is
    # independent of label identity (convergence is gated separately by
    # convergence_scale.py)
    rng = np.random.RandomState(1)
    ds = Dataset(
        graph=graph,
        features=rng.rand(args.nodes, layers[0]).astype(np.float32),
        labels=rng.randint(0, layers[-1],
                           size=args.nodes).astype(np.int32),
        mask=rng.choice([1, 2, 3], size=args.nodes,
                        p=[0.66, 0.10, 0.24]).astype(np.int32),
        num_classes=layers[-1],
        name=f"community-{args.graph}+{args.reorder}")

    dtype, compute_dtype = resolve_dtypes(args.dtype)
    rows = {}
    for spec in args.impls.split(","):
        # 'IMPL+fuse' races the fused-normalization path (table-baked
        # D^-1/2 + fused epilogue) against the bare 'IMPL' row — the
        # epoch-level form of micro_agg.py's chain-/fused- rows
        impl, _, fuse_tag = spec.partition("+")
        if fuse_tag not in ("", "fuse"):
            print(f"# unknown impl spec {spec!r} (IMPL or IMPL+fuse)",
                  file=sys.stderr)
            continue
        cfg = TrainConfig(learning_rate=0.01, weight_decay=1e-4,
                          decay_rate=0.97, decay_steps=100,
                          aggr_impl=impl, dtype=dtype,
                          compute_dtype=compute_dtype,
                          aggr_fuse="on" if fuse_tag else "off",
                          bdense_min_fill=args.min_fill,
                          bdense_a_budget=args.a_budget or None,
                          bdense_group=args.bdense_group,
                          verbose=False, eval_every=1 << 30,
                          symmetric=True)
        t0 = time.time()
        trainer = Trainer(build_gcn(layers, dropout_rate=0.5), ds, cfg)
        trainer.train(epochs=2)   # compile lap + warmup
        trainer.sync()
        compile_s = time.time() - t0
        times = []
        for _ in range(args.epochs):
            t0 = time.time()
            trainer.train(epochs=1)
            trainer.sync()
            times.append((time.time() - t0) * 1000.0)
        row = {"compile_s": round(compile_s, 1),
               "epoch_ms": round(float(np.median(times)), 2),
               "epoch_ms_all": [round(t, 1) for t in times]}
        if fuse_tag:
            row["aggr_fuse"] = "on"
        if impl == "bdense":
            row["min_fill"] = args.min_fill
            row["a_budget"] = args.a_budget
            if args.bdense_group > 1:
                row["bdense_group"] = args.bdense_group
        rows[spec] = row
        print(f"# {spec}: epoch {row['epoch_ms']} ms "
              f"(compile {compile_s:.0f}s)", file=sys.stderr)
        del trainer

    line = {"metric": METRIC,
            "V": args.nodes, "E": int(graph.num_edges),
            "layers": args.layers, "dtype": args.dtype,
            "graph": args.graph, "reorder": args.reorder,
            "gen_s": round(gen_s, 1), "reorder_s": round(reorder_s, 1),
            "platform": dev.platform, "device_kind": dev.device_kind,
            "impls": rows,
            "labels": "synthetic_random (timing only; convergence is "
                      "convergence_scale.py's gate)"}
    if not args.cpu and dev.platform in ("tpu", "axon"):
        try:
            with open(_BASELINES) as f:
                db = json.load(f)
        except (OSError, ValueError):
            db = {}
        rec = dict(line)
        rec["recorded"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
        rec["provenance"] = ("benchmarks/epoch_community.py --graph "
                             f"{args.graph} --reorder {args.reorder} "
                             f"--dtype {args.dtype} --min-fill "
                             f"{args.min_fill}")
        db[METRIC] = rec
        tmp = _BASELINES + ".tmp"
        with open(tmp, "w") as f:
            json.dump(db, f, indent=1, sort_keys=True)
        os.replace(tmp, _BASELINES)
        print(f"# recorded -> {_BASELINES}", file=sys.stderr)
    print(json.dumps(line))
    return 0


if __name__ == "__main__":
    sys.exit(main())
